"""Per-request structured log context.

Python analog of the reference's per-controller log constructor
(``/root/reference/internal/controller/util.go:28-41``): every log line a
reconcile emits carries the controller name (lowercased kind, the same
value the prometheus ``controller`` label uses) and the request's
namespaced name — as structured ``key=value`` fields rendered ahead of the
message, not hand-interpolated into each format string.

Usage::

    log = request_logger("cron", namespace=ns, name=name)
    log.info("created %s %s", kind, wname)
    # → [controller=cron cron=ns/name] created JAXJob x-123

    log = request_logger("cron", namespace=ns, name=name, trace=trace_id)
    log.info("created %s %s", kind, wname)
    # → [controller=cron cron=ns/name trace=ab12…] created JAXJob x-123
"""

from __future__ import annotations

import logging
from typing import Any, MutableMapping, Optional, Tuple


class _ContextAdapter(logging.LoggerAdapter):
    """Prefixes every record with the adapter's key=value context."""

    def process(
        self, msg: str, kwargs: MutableMapping[str, Any]
    ) -> Tuple[str, MutableMapping[str, Any]]:
        ctx = " ".join(f"{k}={v}" for k, v in (self.extra or {}).items())
        return (f"[{ctx}] {msg}", kwargs) if ctx else (msg, kwargs)


def request_logger(
    controller: str,
    namespace: Optional[str] = None,
    name: Optional[str] = None,
    trace: Optional[str] = None,
    **fields: Any,
) -> logging.LoggerAdapter:
    """Logger for one reconcile request.

    ``controller`` is the lowercased kind (prometheus-compatible — the
    reference lowercases for the same reason, ``util.go:33-36``); the
    namespaced name is recorded under the controller name as key, matching
    the reference's ``WithValues(strings.ToLower(kind), req.NamespacedName)``.
    ``trace`` is the tick's trace id (telemetry.new_trace_id); it renders
    as a ``trace=`` field so log lines correlate with ``/debug/traces``
    spans. Field order is fixed: ``controller``, the namespaced name,
    ``trace``, then extra ``fields`` in keyword order (e.g. ``job="ns/x"``).
    """
    controller = controller.lower()
    base = logging.getLogger(f"controller.{controller}")
    extra: "dict[str, Any]" = {"controller": controller}
    if name is not None:
        extra[controller] = f"{namespace}/{name}" if namespace else name
    if trace is not None:
        extra["trace"] = trace
    extra.update(fields)
    return _ContextAdapter(base, extra)


__all__ = ["request_logger"]
