"""Minimal Helm-template renderer for the project chart.

``helm template``-compatible rendering of ``charts/cron-operator-tpu`` in
pure stdlib Python: the chart stays a standard Helm chart (installable with
real helm), while environments without the helm binary — this build image,
the CI gate, the chart unit tests — can still render and pin the
values→flags mapping (the reference pins it with helm-unittest:
``/root/reference/charts/cron-operator/tests/deployment_test.yaml``).

Supported template subset (the chart is authored against exactly this):

- actions ``{{ ... }}`` with ``{{-``/``-}}`` whitespace trimming;
- paths ``.Values.a.b``, ``.Chart.Name``/``.Chart.Version``/``.Chart.AppVersion``,
  ``.Release.Name``/``.Release.Namespace``, and bare ``.`` (current scope);
- pipelines with ``default``, ``quote``, ``toYaml``, ``nindent``, ``indent``,
  ``trunc``, ``trimSuffix``, ``lower``, ``toString``;
- ``include "name" .`` of ``{{ define }}`` blocks from ``_helpers.tpl``;
- ``printf "fmt" args...`` (%s/%d), ``eq``, ``not``;
- blocks: ``if``/``else``/``end``, ``with``/``end`` (rebinds ``.``).

``range`` is intentionally unsupported — list-valued values are emitted via
``toYaml``, which keeps templates in the subset and output deterministic.

CLI: ``python -m cron_operator_tpu.utils.helmtmpl CHART_DIR [--set k=v ...]
[--values FILE] [--release NAME] [--namespace NS]`` prints the rendered
multi-document YAML exactly like ``helm template``.
"""

from __future__ import annotations

import re
import shlex
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

import yaml

_ACTION = re.compile(r"\{\{-?\s*(.*?)\s*-?\}\}", re.S)


def _split_actions(src: str) -> List[Tuple[str, str]]:
    """Template source → [(kind, payload)]: kind 'text' or 'action'.

    ``{{-`` trims ALL trailing whitespace from the preceding text and
    ``-}}`` ALL leading whitespace from the following text — Go template
    semantics, which the chart's YAML layout relies on."""
    parts: List[Tuple[str, str]] = []
    pos = 0
    trim_next = False
    while True:
        m = _ACTION.search(src, pos)
        if not m:
            text = src[pos:]
            parts.append(("text", text.lstrip() if trim_next else text))
            return parts
        text = src[pos:m.start()]
        if trim_next:
            text = text.lstrip()
        if m.group(0).startswith("{{-"):
            text = text.rstrip()
        parts.append(("text", text))
        parts.append(("action", m.group(1).strip()))
        trim_next = m.group(0).endswith("-}}")
        pos = m.end()


class _Scope:
    """The template context: ``.`` plus Values/Chart/Release roots."""

    def __init__(self, root: Dict[str, Any], dot: Any = None):
        self.root = root
        self.dot = root if dot is None else dot

    def rebind(self, dot: Any) -> "_Scope":
        return _Scope(self.root, dot)

    def resolve(self, path: str) -> Any:
        if path == ".":
            return self.dot
        cur: Any = self.root if path.startswith(".Values") or \
            path.startswith(".Chart") or path.startswith(".Release") else None
        if cur is None:
            # relative to dot (e.g. inside `with`)
            cur = self.dot
            segments = path.lstrip(".").split(".")
        else:
            segments = path.lstrip(".").split(".")
        for seg in segments:
            if not seg:
                continue
            if isinstance(cur, dict):
                cur = cur.get(seg)
            else:
                cur = getattr(cur, seg, None)
            if cur is None:
                return None
        return cur


def _truthy(v: Any) -> bool:
    return bool(v) and v != {} and v != []


def _to_yaml(v: Any) -> str:
    return yaml.safe_dump(v, default_flow_style=False, sort_keys=False).rstrip("\n")


def _fmt(v: Any) -> str:
    if v is None:
        return ""
    if isinstance(v, bool):
        return "true" if v else "false"
    return str(v)


class Renderer:
    def __init__(self, chart_dir: Path, values: Dict[str, Any],
                 release: str = "release-name", namespace: str = "default"):
        self.chart_dir = Path(chart_dir)
        meta = yaml.safe_load((self.chart_dir / "Chart.yaml").read_text())
        self.context: Dict[str, Any] = {
            "Values": values,
            "Chart": {
                "Name": meta.get("name", ""),
                "Version": str(meta.get("version", "")),
                "AppVersion": str(meta.get("appVersion", "")),
            },
            "Release": {"Name": release, "Namespace": namespace},
        }
        self.defines: Dict[str, List[Tuple[str, str]]] = {}
        for tpl in sorted((self.chart_dir / "templates").glob("*.tpl")):
            self._collect_defines(tpl.read_text())

    # -- defines ------------------------------------------------------------

    def _collect_defines(self, src: str) -> None:
        parts = _split_actions(src)
        i = 0
        while i < len(parts):
            kind, payload = parts[i]
            if kind == "action" and payload.startswith("define "):
                name = shlex.split(payload[len("define "):])[0]
                depth, body = 1, []
                i += 1
                while i < len(parts):
                    k, p = parts[i]
                    if k == "action":
                        head = p.split()[0] if p.split() else ""
                        if head in ("define", "if", "with", "range"):
                            depth += 1
                        elif head == "end":
                            depth -= 1
                            if depth == 0:
                                break
                    body.append((k, p))
                    i += 1
                self.defines[name] = body
            i += 1

    # -- expression evaluation ----------------------------------------------

    def _eval_atom(self, tokens: List[str], scope: _Scope) -> Any:
        """Evaluate one function-call or literal from ``tokens``."""
        head, args = tokens[0], tokens[1:]
        if head.startswith('"') or head.startswith("'"):
            assert not args, f"unexpected args after literal: {tokens}"
            return head[1:-1]
        if re.fullmatch(r"-?\d+", head):
            return int(head)
        if head in ("true", "false"):
            return head == "true"
        if head.startswith("."):
            assert not args, f"unexpected args after path: {tokens}"
            return scope.resolve(head)
        if head == "include":
            name = self._eval_atom([args[0]], scope)
            assert args[1] == ".", "include supports only '.' context"
            return self._render_parts(self.defines[name], scope)
        if head == "printf":
            fmt = self._eval_atom([args[0]], scope)
            vals = [self._eval_atom([a], scope) for a in args[1:]]
            return fmt.replace("%d", "%s") % tuple(_fmt(v) for v in vals)
        if head == "not":
            return not _truthy(self._eval_atom(args, scope))
        if head == "eq":
            a, b = (self._eval_atom([t], scope) for t in args[:2])
            return a == b
        if head == "toYaml":
            return _to_yaml(self._eval_atom(args, scope))
        raise ValueError(f"unsupported template function {head!r}")

    def _eval(self, expr: str, scope: _Scope) -> Any:
        stages = [shlex.split(s, posix=False)
                  for s in self._split_pipeline(expr)]
        value = self._eval_atom(stages[0], scope)
        for stage in stages[1:]:
            fn, args = stage[0], stage[1:]
            if fn == "default":
                dflt = self._eval_atom(args, scope)
                value = value if _truthy(value) else dflt
            elif fn == "quote":
                value = '"%s"' % _fmt(value)
            elif fn == "toYaml":
                value = _to_yaml(value)
            elif fn == "nindent":
                n = int(args[0])
                pad = " " * n
                value = "\n" + "\n".join(
                    pad + ln if ln else ln for ln in _fmt(value).split("\n")
                )
            elif fn == "indent":
                n = int(args[0])
                pad = " " * n
                value = "\n".join(
                    pad + ln if ln else ln for ln in _fmt(value).split("\n")
                )
            elif fn == "trunc":
                value = _fmt(value)[: int(args[0])]
            elif fn == "trimSuffix":
                suf = self._eval_atom(args, scope)
                v = _fmt(value)
                value = v[: -len(suf)] if suf and v.endswith(suf) else v
            elif fn == "lower":
                value = _fmt(value).lower()
            elif fn == "toString":
                value = _fmt(value)
            else:
                raise ValueError(f"unsupported pipeline function {fn!r}")
        return value

    @staticmethod
    def _split_pipeline(expr: str) -> List[str]:
        out, depth, cur = [], 0, []
        quote = None
        for ch in expr:
            if quote:
                if ch == quote:
                    quote = None
                cur.append(ch)
            elif ch in "\"'":
                quote = ch
                cur.append(ch)
            elif ch == "(":
                depth += 1
                cur.append(ch)
            elif ch == ")":
                depth -= 1
                cur.append(ch)
            elif ch == "|" and depth == 0:
                out.append("".join(cur).strip())
                cur = []
            else:
                cur.append(ch)
        out.append("".join(cur).strip())
        return out

    # -- block structure -----------------------------------------------------

    def _render_parts(self, parts: List[Tuple[str, str]], scope: _Scope) -> str:
        out: List[str] = []
        i = 0
        while i < len(parts):
            kind, payload = parts[i]
            if kind == "text":
                out.append(payload)
                i += 1
                continue
            head = payload.split()[0] if payload.split() else ""
            if head in ("if", "with"):
                block, else_block, i = self._collect_block(parts, i)
                cond_expr = payload[len(head):].strip()
                value = self._eval(cond_expr, scope)
                if _truthy(value):
                    inner = scope.rebind(value) if head == "with" else scope
                    out.append(self._render_parts(block, inner))
                elif else_block is not None:
                    out.append(self._render_parts(else_block, scope))
            elif head == "define":
                # skip nested define bodies in output position
                _, _, i = self._collect_block(parts, i)
            elif head in ("end", "else"):
                raise ValueError(f"unbalanced {head!r}")
            else:
                val = self._eval(payload, scope)
                out.append(_fmt(val))
                i += 1
        return "".join(out)

    def _collect_block(self, parts, i):
        """From the opener at ``i``, collect body (and else-body) through the
        matching end; returns (body, else_body_or_None, next_index)."""
        depth = 1
        body: List[Tuple[str, str]] = []
        else_body: Optional[List[Tuple[str, str]]] = None
        cur = body
        i += 1
        while i < len(parts):
            k, p = parts[i]
            if k == "action":
                h = p.split()[0] if p.split() else ""
                if h in ("if", "with", "range", "define"):
                    depth += 1
                elif h == "else" and depth == 1:
                    else_body = []
                    cur = else_body
                    i += 1
                    continue
                elif h == "end":
                    depth -= 1
                    if depth == 0:
                        return body, else_body, i + 1
            cur.append((k, p))
            i += 1
        raise ValueError("unterminated block")

    # -- entry ---------------------------------------------------------------

    def render(self) -> Dict[str, str]:
        """Render every non-helper template; returns {relative path: text}."""
        scope = _Scope(self.context)
        out: Dict[str, str] = {}
        for tpl in sorted((self.chart_dir / "templates").glob("*.yaml")):
            text = self._render_parts(_split_actions(tpl.read_text()), scope)
            if text.strip():
                out[f"templates/{tpl.name}"] = text
        return out

    def render_objects(self) -> List[Dict[str, Any]]:
        objs: List[Dict[str, Any]] = []
        for text in self.render().values():
            for doc in yaml.safe_load_all(text):
                if doc:
                    objs.append(doc)
        return objs


def _deep_merge(base: Dict[str, Any], over: Dict[str, Any]) -> Dict[str, Any]:
    out = dict(base)
    for k, v in over.items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = _deep_merge(out[k], v)
        else:
            out[k] = v
    return out


def load_values(chart_dir: Path, overrides: Optional[Dict[str, Any]] = None,
                extra_files: Optional[List[Path]] = None) -> Dict[str, Any]:
    values = yaml.safe_load((Path(chart_dir) / "values.yaml").read_text()) or {}
    for f in extra_files or []:
        values = _deep_merge(values, yaml.safe_load(Path(f).read_text()) or {})
    return _deep_merge(values, overrides or {})


def _set_path(values: Dict[str, Any], dotted: str, raw: str) -> None:
    keys = dotted.split(".")
    cur = values
    for k in keys[:-1]:
        cur = cur.setdefault(k, {})
    try:
        val: Any = yaml.safe_load(raw)
    except yaml.YAMLError:
        val = raw
    cur[keys[-1]] = val


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    import sys

    p = argparse.ArgumentParser(
        prog="helmtmpl", description="render the project Helm chart"
    )
    p.add_argument("chart", help="chart directory")
    p.add_argument("--set", action="append", default=[], metavar="K=V")
    p.add_argument("--values", action="append", default=[], metavar="FILE")
    p.add_argument("--release", default="cron-operator-tpu")
    p.add_argument("--namespace", default="default")
    args = p.parse_args(argv)

    overrides: Dict[str, Any] = {}
    for s in args.set:
        k, _, v = s.partition("=")
        _set_path(overrides, k, v)
    values = load_values(Path(args.chart), overrides,
                         [Path(f) for f in args.values])
    r = Renderer(Path(args.chart), values, release=args.release,
                 namespace=args.namespace)
    for name, text in r.render().items():
        sys.stdout.write(f"---\n# Source: {name}\n{text.strip()}\n")
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
