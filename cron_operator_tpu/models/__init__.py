"""Model zoo for the scheduled workloads and benchmarks.

These are the JAX analogs of the training containers the reference
operator's example Crons launch (``/root/reference/examples/v1alpha1/cron/``
runs PyTorch/TF MNIST-style images): an MLP for the MNIST acceptance
configs, ResNet-50 for the v5e-16 north-star benchmark, BERT for the
long-context / v5e-64 config (BASELINE.md acceptance configs 1-5), GPT
(causal LM, optional MoE blocks) and ViT (attention on images, sharing
BERT's encoder stack).

All models are flax.linen modules with bf16 compute / f32 params by
default (MXU-native), static shapes, and no Python control flow in the
traced path.
"""

from cron_operator_tpu.models.mlp import MLP
from cron_operator_tpu.models.resnet import ResNet, ResNet18, ResNet50
from cron_operator_tpu.models.bert import Bert, BertConfig
from cron_operator_tpu.models.gpt import GPT, GPTConfig
from cron_operator_tpu.models.vit import ViT, ViTConfig

__all__ = [
    "MLP", "ResNet", "ResNet18", "ResNet50", "Bert", "BertConfig",
    "GPT", "GPTConfig", "ViT", "ViTConfig",
]
