"""Vision Transformer — attention on images, completing the zoo's coverage
of the two data modalities × two architectures the acceptance workloads
span (conv/image: ResNet; attention/text: BERT, GPT; attention/image: this).

Reuses BERT's :class:`~cron_operator_tpu.models.bert.EncoderLayer`
unchanged (the config is duck-typed — same field names), inheriting the
bf16-compute/f32-param convention and the attention dispatcher. Note the
token count is ``(size/patch)² + 1`` (CLS) — e.g. 197 for base/224 —
which is never 128-aligned, so the dispatcher's ``auto`` resolves to XLA
dense attention here (the right call regardless: at ~200 tokens dense
wins; see ``ops/attention.py``'s crossover) and ``flash``/``ring``/
``ulysses`` cannot be forced. The patch stem is one strided conv —
MXU-native, exactly how the TPU wants patchification (no gather/reshape
gymnastics).

Reference parity note: the reference operator schedules arbitrary
workload containers (examples are PyTorch/TF MNIST-style scripts,
``/root/reference/examples/v1alpha1/cron/``); the model zoo is this
build's in-tree analog of those containers.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from cron_operator_tpu.models.bert import EncoderLayer


@dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    num_classes: int = 1000
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    mlp_dim: int = 3072
    dtype: Any = jnp.bfloat16
    attention_impl: str = "auto"  # auto | flash | xla | ring | ulysses
    attention_interpret: bool = False
    # Same semantics as BertConfig (the encoder layer is shared): GQA
    # head grouping and rotary positions over the flattened patch index.
    num_kv_heads: int = 0
    rope: bool = False

    @staticmethod
    def base(**overrides) -> "ViTConfig":
        return ViTConfig(**overrides)

    @staticmethod
    def tiny(**overrides) -> "ViTConfig":
        defaults = dict(
            image_size=32, patch_size=8, num_classes=10, hidden_size=64,
            num_layers=2, num_heads=4, mlp_dim=256,
        )
        defaults.update(overrides)
        return ViTConfig(**defaults)


class ViT(nn.Module):
    """NHWC images ``[batch, size, size, 3]`` → logits ``[batch, classes]``."""

    config: ViTConfig = field(default_factory=ViTConfig)
    mesh: Optional[jax.sharding.Mesh] = None

    @nn.compact
    def __call__(self, images: jnp.ndarray) -> jnp.ndarray:
        cfg = self.config
        if images.shape[1] % cfg.patch_size or images.shape[2] % cfg.patch_size:
            raise ValueError(
                f"image {images.shape[1]}x{images.shape[2]} not divisible "
                f"by patch size {cfg.patch_size}"
            )
        # Patchify = one strided conv onto the hidden dim.
        x = nn.Conv(
            cfg.hidden_size, (cfg.patch_size, cfg.patch_size),
            strides=(cfg.patch_size, cfg.patch_size),
            dtype=cfg.dtype, name="patch_embed",
        )(images.astype(cfg.dtype))
        b = x.shape[0]
        n = x.shape[1] * x.shape[2]
        x = x.reshape(b, n, cfg.hidden_size)

        cls = self.param(
            "cls_token", nn.initializers.zeros, (1, 1, cfg.hidden_size)
        )
        x = jnp.concatenate(
            [jnp.broadcast_to(cls, (b, 1, cfg.hidden_size)).astype(cfg.dtype),
             x],
            axis=1,
        )
        if not cfg.rope:  # rotary (in the shared encoder layer) replaces
            pos = self.param(  # the learned absolute table
                "pos_emb", nn.initializers.normal(0.02),
                (n + 1, cfg.hidden_size),
            )
            x = x + pos[None].astype(cfg.dtype)

        for i in range(cfg.num_layers):
            x = EncoderLayer(cfg, mesh=self.mesh, name=f"layer_{i}")(x)
        x = nn.LayerNorm(dtype=cfg.dtype)(x)
        # Classification head on the CLS token; f32 logits for a stable
        # softmax-cross-entropy.
        return nn.Dense(cfg.num_classes, dtype=jnp.float32, name="head")(
            x[:, 0].astype(jnp.float32)
        )


__all__ = ["ViT", "ViTConfig"]
