"""Shared attention-projection building block for the model zoo.

One implementation of the Q/K/V projection contract all transformer
families use (BERT/ViT encoder, GPT decoder): fused ``qkv`` for MHA
(keeps param trees byte-compatible with checkpoints that predate GQA),
split ``q`` + ``kv`` projections for grouped-query configs, and rotary
position application when the config asks for it. Factored here so the
GQA/RoPE semantics cannot drift between the families.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from cron_operator_tpu.ops.rope import apply_rope


def grouped_qkv_projection(
    cfg, y: jnp.ndarray, rope_positions: Optional[jax.Array] = None
):
    """Project ``y [b, s, hidden]`` → (q, k, v) per ``cfg``.

    ``cfg`` needs ``hidden_size``, ``num_heads``, ``num_kv_heads``
    (0 = MHA), ``dtype`` and ``rope``. Must be called inside a flax
    compact context (creates the projection submodules). When
    ``cfg.rope``, Q/K are rotated at ``rope_positions`` (defaults to
    ``arange(s)``; decode passes its single cache position).
    """
    head_dim = cfg.hidden_size // cfg.num_heads
    kv_heads = cfg.num_kv_heads or cfg.num_heads
    if kv_heads < 1 or cfg.num_heads % kv_heads:
        raise ValueError(
            f"num_kv_heads {kv_heads} must be a positive divisor of "
            f"num_heads {cfg.num_heads}"
        )
    if kv_heads == cfg.num_heads:
        qkv = nn.DenseGeneral(
            (3, cfg.num_heads, head_dim), axis=-1, dtype=cfg.dtype,
            name="qkv",
        )(y)
        q, k, v = (qkv[:, :, i] for i in range(3))  # each [b, s, h, d]
    else:
        q = nn.DenseGeneral(
            (cfg.num_heads, head_dim), axis=-1, dtype=cfg.dtype, name="q"
        )(y)
        kv = nn.DenseGeneral(
            (2, kv_heads, head_dim), axis=-1, dtype=cfg.dtype, name="kv"
        )(y)
        k, v = kv[:, :, 0], kv[:, :, 1]
    if cfg.rope:
        positions = (
            jnp.arange(y.shape[1]) if rope_positions is None
            else rope_positions
        )
        q = apply_rope(q, positions)
        k = apply_rope(k, positions)
    return q, k, v


__all__ = ["grouped_qkv_projection"]
