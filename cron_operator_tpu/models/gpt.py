"""GPT-style decoder — the causal-LM / long-context flagship.

The reference schedules third-party training images and never sees a
model; this framework's workloads are first-class, and the decoder is
where its long-context machinery composes: causal attention through the
pluggable :func:`ops.attention.multi_head_attention` (XLA → Pallas flash →
ring over the mesh ``seq`` axis — same model code for all three), and an
optional Switch-MoE FFN every ``moe_every`` blocks using
:mod:`parallel.moe` (expert weights shard over the ``expert`` mesh axis;
GSPMD turns dispatch/combine into all-to-alls).

Next-token objective with tied output embedding — the loss path ends in a
vocab-sized matmul, the realistic MXU load profile.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from cron_operator_tpu.models.layers import grouped_qkv_projection
from cron_operator_tpu.ops.attention import multi_head_attention
from cron_operator_tpu.parallel.moe import moe_ffn


@dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 50257
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    mlp_dim: int = 3072
    max_len: int = 1024
    dtype: Any = jnp.bfloat16
    attention_impl: str = "auto"  # auto | flash | xla | ring | ulysses
    attention_interpret: bool = False  # CPU tests of the Pallas path
    # Grouped-query attention: 0 (default) means MHA (= num_heads, and
    # the fused qkv projection layout stays byte-compatible with earlier
    # checkpoints). A divisor of num_heads shares each K/V head across
    # num_heads/num_kv_heads query heads — the KV cache (the serving
    # memory bill) shrinks by that factor.
    num_kv_heads: int = 0
    # Rotary position embeddings on Q/K (relative positions); the learned
    # absolute pos_emb table is skipped when on.
    rope: bool = False
    # MoE: 0 disables; k > 0 replaces every k-th block's FFN with a
    # Switch-MoE layer of ``num_experts`` experts.
    moe_every: int = 0
    num_experts: int = 8
    moe_capacity_factor: float = 1.25
    # Weight of the router load-balancing loss, folded into the model's
    # scalar aux output (trainer adds it to the task loss).
    moe_aux_weight: float = 0.01
    # Return final hidden states instead of logits, for trainers that
    # compute the loss with ops.xent.chunked_cross_entropy against the
    # tied embedding — skips materializing [b, s, vocab] logits entirely
    # (the dominant HBM spike at long context).
    return_hidden: bool = False

    @staticmethod
    def tiny(**overrides) -> "GPTConfig":
        defaults = dict(
            vocab_size=1024, hidden_size=128, num_layers=2, num_heads=4,
            mlp_dim=512, max_len=512,
        )
        defaults.update(overrides)
        return GPTConfig(**defaults)


class MoEBlock(nn.Module):
    """Switch-MoE FFN as a flax module around :func:`parallel.moe.moe_ffn`.

    Param shapes match ``init_moe_params``. Sharding: the module is named
    ``"moe"``, which :func:`parallel.mesh.sharding_for_tree` recognizes —
    on a mesh with an ``expert`` axis the [E, ...] weights get
    ``P('expert')`` and GSPMD lowers dispatch/combine to all-to-alls.
    Expert matmuls run in ``cfg.dtype`` (bf16 on TPU); only routing is f32.
    """

    config: GPTConfig
    # Decode steps route a batch-sized token pool; the training capacity
    # factor over so few tokens drops colliding rows (capacity 1). Decode
    # raises the factor to num_experts — capacity = batch, no drops ever —
    # which is cheap at serving batch sizes and keeps cached decode
    # numerically aligned with a no-drop forward.
    decode: bool = False

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> tuple:
        cfg = self.config
        d, e = cfg.hidden_size, cfg.num_experts
        params = {
            "router": self.param(
                "router", nn.initializers.normal(0.02), (d, e)
            ),
            "wi": self.param(
                "wi", nn.initializers.lecun_normal(), (e, d, cfg.mlp_dim)
            ),
            "wo": self.param(
                "wo", nn.initializers.lecun_normal(), (e, cfg.mlp_dim, d)
            ),
        }
        b, s, _ = x.shape
        flat = x.reshape(b * s, d)
        cf = (
            max(cfg.moe_capacity_factor, float(e)) if self.decode
            else cfg.moe_capacity_factor
        )
        y, aux = moe_ffn(
            params, flat, capacity_factor=cf,
            compute_dtype=cfg.dtype,
        )
        return y.reshape(b, s, d).astype(cfg.dtype), aux


class DecoderLayer(nn.Module):
    config: GPTConfig
    mesh: Optional[jax.sharding.Mesh] = None
    use_moe: bool = False
    # Serving modes (training uses neither): ``prefill`` runs the normal
    # batched causal forward AND writes the whole prompt's K/V into the
    # layer's cache in one pass; ``decode`` processes ONE token [b, 1, d]
    # against that cache. The position index comes from the caller (one
    # counter at the GPT level — per-layer counters kept in implicit
    # lockstep would desynchronize silently if a layer were ever skipped).
    decode: bool = False
    prefill: bool = False

    @nn.compact
    def __call__(
        self, x: jnp.ndarray, pos_idx: Optional[jnp.ndarray] = None
    ) -> tuple:
        cfg = self.config

        y = nn.LayerNorm(dtype=cfg.dtype)(x)
        # Shared GQA/RoPE projection contract (models/layers.py); decode
        # rotates at the single cache position instead of arange.
        q, k, v = grouped_qkv_projection(
            cfg, y,
            rope_positions=(
                pos_idx[None] if (self.decode and cfg.rope) else None
            ),
        )

        if self.decode:
            attn = self._decode_attention(q, k, v, pos_idx)
        else:
            # Grouped K/V go to the dispatcher as-is: the flash kernel
            # consumes the layout natively; the other impls broadcast
            # internally. The prefill cache always stores kv_heads.
            attn = multi_head_attention(
                q, k, v, causal=True, impl=cfg.attention_impl,
                mesh=self.mesh, interpret=cfg.attention_interpret,
            )
            if self.prefill:
                self._write_prefill_cache(k, v)
        attn = nn.DenseGeneral(
            cfg.hidden_size, axis=(-2, -1), dtype=cfg.dtype, name="out"
        )(attn)
        x = x + attn

        y = nn.LayerNorm(dtype=cfg.dtype)(x)
        aux = jnp.zeros((), jnp.float32)
        if self.use_moe:
            y, aux = MoEBlock(cfg, decode=self.decode, name="moe")(y)
        else:
            y = nn.Dense(cfg.mlp_dim, dtype=cfg.dtype)(y)
            y = nn.gelu(y)
            y = nn.Dense(cfg.hidden_size, dtype=cfg.dtype)(y)
        return x + y, aux

    def _cache_vars(self, b, kv_heads, d):
        # GQA caches only kv_heads — the serving memory saving.
        cfg = self.config
        def zeros():
            return jnp.zeros((b, cfg.max_len, kv_heads, d), cfg.dtype)
        return (
            self.variable("cache", "k", zeros),
            self.variable("cache", "v", zeros),
        )

    def _write_prefill_cache(self, k, v):
        """Batched cache fill: the whole prompt's K/V in ONE pass (a
        per-token prefill would stream the full parameter set p times)."""
        cfg = self.config
        b, p, h, d = k.shape
        cache_k, cache_v = self._cache_vars(b, h, d)
        cache_k.value = jax.lax.dynamic_update_slice(
            cache_k.value, k.astype(cfg.dtype), (0, 0, 0, 0)
        )
        cache_v.value = jax.lax.dynamic_update_slice(
            cache_v.value, v.astype(cfg.dtype), (0, 0, 0, 0)
        )

    def _decode_attention(self, q, k, v, pos_idx):
        """One-token attention against the layer's KV cache.

        Cache layout ``[b, max_len, heads, head_dim]`` in ``cfg.dtype``
        — the decode state is one pytree the driver carries through
        ``lax.scan``. Static shapes throughout: the new K/V land via
        dynamic_update_slice at ``pos_idx`` and masking (not slicing)
        excludes the unwritten tail — the XLA-friendly decode shape (no
        data-dependent dims; one [1, max_len] row per head,
        bandwidth-bound as decode always is).
        """
        cfg = self.config
        b, one, h, d = q.shape
        kv_h = k.shape[2]
        assert one == 1, "decode processes one token per call"
        assert pos_idx is not None, "decode needs the position index"
        cache_k, cache_v = self._cache_vars(b, kv_h, d)
        cache_k.value = jax.lax.dynamic_update_slice(
            cache_k.value, k.astype(cfg.dtype), (0, pos_idx, 0, 0)
        )
        cache_v.value = jax.lax.dynamic_update_slice(
            cache_v.value, v.astype(cfg.dtype), (0, pos_idx, 0, 0)
        )

        scale = 1.0 / (d ** 0.5)
        group = h // kv_h
        # Grouped einsum: each KV head serves `group` query heads without
        # materializing a repeated cache (GQA reads kv_h×, not h×).
        qg = q.reshape(b, kv_h, group, d)
        scores = jnp.einsum(
            "bkgd,bskd->bkgs", qg, cache_k.value,
            preferred_element_type=jnp.float32,
        ) * scale  # [b, kv_h, group, max_len]
        mask = jnp.arange(cfg.max_len) <= pos_idx  # written positions
        scores = jnp.where(mask[None, None, None, :], scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1).astype(cfg.dtype)
        # f32 accumulation: the reduction runs over max_len positions, so
        # bf16 partial sums would lose precision on long contexts (ADVICE
        # r4) — accumulate f32, store back in the compute dtype.
        out = jnp.einsum(
            "bkgs,bskd->bkgd", probs, cache_v.value,
            preferred_element_type=jnp.float32,
        ).astype(cfg.dtype)
        return out.reshape(b, 1, h, d)


class GPT(nn.Module):
    """Token ids ``[batch, seq]`` → (output, aux loss scalar).

    ``output`` is next-token logits ``[b, s, vocab]`` by default; with
    ``cfg.return_hidden`` it is the pair ``(hidden [b, s, d] in
    cfg.dtype, tied embedding table [vocab, d])`` for trainers that
    compute the loss via :func:`ops.xent.chunked_cross_entropy` (the
    table comes from the model so callers never hard-code param paths).
    The aux scalar is the weighted MoE router balance loss (0.0 for
    dense configs) — trainers add it to the task loss."""

    config: GPTConfig = field(default_factory=GPTConfig)
    mesh: Optional[jax.sharding.Mesh] = None
    # Serving modes: ``prefill`` consumes the whole prompt [b, p] in one
    # batched pass while populating the per-layer KV caches (flax "cache"
    # collection, created on the first mutable apply); ``decode`` takes
    # ONE token [b, 1] per step against those caches. A single position
    # counter ("cache"/"step") lives here and is passed down to every
    # layer. See workloads/generate.py for the scan driver.
    decode: bool = False
    prefill: bool = False

    @nn.compact
    def __call__(self, input_ids: jnp.ndarray) -> tuple:
        cfg = self.config
        tok = nn.Embed(
            cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype, name="tok_emb"
        )
        # RoPE replaces the learned absolute table entirely (positions
        # rotate Q/K inside each layer instead).
        pos = None if cfg.rope else self.param(
            "pos_emb",
            nn.initializers.normal(0.02),
            (cfg.max_len, cfg.hidden_size),
        )
        s = input_ids.shape[1]
        pos_idx = None
        if self.decode or self.prefill:
            step = self.variable(
                "cache", "step", lambda: jnp.zeros((), jnp.int32)
            )
        if self.decode:
            pos_idx = step.value  # tokens consumed so far
            step.value = pos_idx + 1
            x = tok(input_ids)
            if pos is not None:
                p = jax.lax.dynamic_slice(
                    pos, (pos_idx, 0), (1, cfg.hidden_size)
                )
                x = x + p[None].astype(cfg.dtype)
        else:
            x = tok(input_ids)
            if pos is not None:
                x = x + pos[None, :s].astype(cfg.dtype)
            if self.prefill:
                step.value = jnp.asarray(s, jnp.int32)
        aux_total = jnp.zeros((), jnp.float32)
        for i in range(cfg.num_layers):
            use_moe = (
                cfg.moe_every > 0 and (i + 1) % cfg.moe_every == 0
            )
            x, aux = DecoderLayer(
                cfg, mesh=self.mesh, use_moe=use_moe, decode=self.decode,
                prefill=self.prefill, name=f"layer_{i}",
            )(x, pos_idx)
            aux_total = aux_total + aux
        x = nn.LayerNorm(dtype=cfg.dtype)(x)
        aux_out = cfg.moe_aux_weight * aux_total
        if cfg.return_hidden:
            # Loss-fusion mode: hidden states stay in cfg.dtype (the
            # chunked CE op upcasts per chunk — an f32 copy here would
            # double the residual held across the backward pass at
            # exactly the long-context scale this mode targets) and the
            # tied table travels with them.
            return (x, tok.embedding), aux_out
        logits = tok.attend(x)
        return logits.astype(jnp.float32), aux_out


__all__ = ["GPT", "GPTConfig", "DecoderLayer", "MoEBlock"]
