"""BERT encoder — the long-context / multi-slice workload.

Acceptance config 5 (BASELINE.md: BERT-base JAXJob on v5e-64 with
suspend/deadline/preemption) schedules this model. The attention strategy is
pluggable through :func:`ops.attention.multi_head_attention`: XLA attention
for short sequences, the Pallas flash kernel on TPU, and ring attention over
the mesh's ``seq`` axis for sequences too long for one device — the model
code is identical in all three cases.

Masked-LM objective (tied output embedding) so the loss path ends in a
vocab-sized matmul — the realistic MXU load profile for a scheduling
benchmark.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from cron_operator_tpu.models.layers import grouped_qkv_projection
from cron_operator_tpu.ops.attention import multi_head_attention


@dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    mlp_dim: int = 3072
    max_len: int = 512
    dtype: Any = jnp.bfloat16
    attention_impl: str = "auto"  # auto | flash | xla | ring | ulysses
    # Run the Pallas kernels under the interpreter — CPU tests of the flash
    # path (forward AND backward) through the full model; never set on TPU.
    attention_interpret: bool = False
    # Grouped-query attention (0 = MHA, fused qkv projection preserved
    # for checkpoint compat) and rotary positions — same semantics as
    # GPTConfig; the dispatcher/flash kernel consume the grouped layout.
    num_kv_heads: int = 0
    rope: bool = False

    @staticmethod
    def base(**overrides) -> "BertConfig":
        return BertConfig(**overrides)

    @staticmethod
    def tiny(**overrides) -> "BertConfig":
        defaults = dict(
            vocab_size=1024, hidden_size=128, num_layers=2, num_heads=4,
            mlp_dim=512, max_len=512,
        )
        defaults.update(overrides)
        return BertConfig(**defaults)


class EncoderLayer(nn.Module):
    config: BertConfig
    mesh: Optional[jax.sharding.Mesh] = None

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        cfg = self.config

        # Pre-LN (trains stably without warmup — fine for benchmarks).
        y = nn.LayerNorm(dtype=cfg.dtype)(x)
        # Shared GQA/RoPE projection contract (models/layers.py) — also
        # what ViT uses through this layer; rotary positions work for
        # bidirectional encoders too (1-D over the flattened patch index
        # in ViT's case).
        q, k, v = grouped_qkv_projection(cfg, y)
        attn = multi_head_attention(
            q, k, v, impl=cfg.attention_impl, mesh=self.mesh,
            interpret=cfg.attention_interpret,
        )
        attn = nn.DenseGeneral(
            cfg.hidden_size, axis=(-2, -1), dtype=cfg.dtype, name="out"
        )(attn)
        x = x + attn

        y = nn.LayerNorm(dtype=cfg.dtype)(x)
        y = nn.Dense(cfg.mlp_dim, dtype=cfg.dtype)(y)
        y = nn.gelu(y)
        y = nn.Dense(cfg.hidden_size, dtype=cfg.dtype)(y)
        return x + y


class Bert(nn.Module):
    """Token ids ``[batch, seq]`` → MLM logits ``[batch, seq, vocab]``."""

    config: BertConfig = field(default_factory=BertConfig)
    mesh: Optional[jax.sharding.Mesh] = None

    @nn.compact
    def __call__(self, input_ids: jnp.ndarray) -> jnp.ndarray:
        cfg = self.config
        tok = nn.Embed(
            cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype, name="tok_emb"
        )
        # RoPE replaces the learned absolute table (same semantics as
        # GPT) — keeping both would double-encode positions.
        pos = None if cfg.rope else self.param(
            "pos_emb",
            nn.initializers.normal(0.02),
            (cfg.max_len, cfg.hidden_size),
        )
        s = input_ids.shape[1]
        x = tok(input_ids)
        if pos is not None:
            x = x + pos[None, :s].astype(cfg.dtype)
        for i in range(cfg.num_layers):
            x = EncoderLayer(cfg, mesh=self.mesh, name=f"layer_{i}")(x)
        x = nn.LayerNorm(dtype=cfg.dtype)(x)
        # Tied output embedding: project back onto the token table.
        logits = tok.attend(x)
        return logits.astype(jnp.float32)


__all__ = ["Bert", "BertConfig", "EncoderLayer"]
