# Operator image — the reference's two-stage build (Dockerfile:1-29,
# ENTRYPOINT ["/manager"]) re-done for the Python control plane: build a
# wheel in a throwaway stage, install it into a slim runtime, run as the
# unprivileged nobody user. The operator's cluster mode needs only stdlib +
# PyYAML (the JAX compute stack lives in the workload image), so this image
# stays small.
#
# Build:  docker build -t cron-operator-tpu:latest .
# The chart (charts/cron-operator-tpu) and deploy/operator.yaml reference
# this image name.
FROM python:3.12-slim AS builder

WORKDIR /src
COPY pyproject.toml ./
COPY cron_operator_tpu/ cron_operator_tpu/
RUN pip wheel --no-cache-dir --no-deps --wheel-dir /wheels .

FROM python:3.12-slim

COPY --from=builder /wheels /wheels
# pyyaml: manifest loading; cryptography: the default-secure /metrics
# self-signed certificate path (cmd_start fails fast with a clear error
# if it is missing and no cert path is provided).
RUN pip install --no-cache-dir /wheels/*.whl pyyaml cryptography \
    && rm -rf /wheels

USER 65534:65534

ENTRYPOINT ["cron-operator-tpu"]
CMD ["start", "--api-server=cluster", "--backend=none"]
