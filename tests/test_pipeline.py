"""Pipeline parallelism (parallel.pipeline): numerical parity with the
sequential program, gradients through the pipelined loop, and composition
with the data axis — on the virtual 8-device CPU mesh (conftest)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cron_operator_tpu.parallel.mesh import mesh_for_devices
from cron_operator_tpu.parallel.pipeline import (
    spmd_pipeline,
    stack_pipeline_stages,
)

WIDTH = 16
N_STAGES = 4


def _stage_fn(p, x):
    return jax.nn.relu(x @ p["w"] + p["b"])


def _stages(key):
    out = []
    for i in range(N_STAGES):
        k1, k2, key = jax.random.split(key, 3)
        out.append({
            "w": jax.random.normal(k1, (WIDTH, WIDTH)) / np.sqrt(WIDTH),
            "b": jax.random.normal(k2, (WIDTH,)) * 0.1,
        })
    return out


def _sequential(stages, x):
    for p in stages:
        x = _stage_fn(p, x)
    return x


@pytest.fixture(scope="module")
def rig():
    stages = _stages(jax.random.PRNGKey(0))
    stacked = stack_pipeline_stages(stages)
    x = jax.random.normal(jax.random.PRNGKey(1), (8, WIDTH))
    return stages, stacked, x


class TestForward:
    def test_matches_sequential_pipe_only(self, rig):
        stages, stacked, x = rig
        mesh = mesh_for_devices(jax.devices()[:4], pipe=4)  # pipe-pure
        y = spmd_pipeline(_stage_fn, stacked, x, mesh=mesh,
                          n_microbatches=4)
        np.testing.assert_allclose(
            np.asarray(y), np.asarray(_sequential(stages, x)),
            rtol=1e-5, atol=1e-5,
        )

    def test_composes_with_data_axis(self, rig):
        stages, stacked, x = rig
        mesh = mesh_for_devices(pipe=4)  # 8 devices → pipe=4 × data=2
        assert dict(zip(mesh.axis_names, mesh.devices.shape)) == {
            "pipe": 4, "data": 2,
        }
        y = jax.jit(
            lambda p, b: spmd_pipeline(_stage_fn, p, b, mesh=mesh,
                                       n_microbatches=2)
        )(stacked, x)
        np.testing.assert_allclose(
            np.asarray(y), np.asarray(_sequential(stages, x)),
            rtol=1e-5, atol=1e-5,
        )

    def test_microbatch_count_must_divide(self, rig):
        _, stacked, x = rig
        mesh = mesh_for_devices(jax.devices()[:4], pipe=4)
        with pytest.raises(ValueError, match="not divisible"):
            spmd_pipeline(_stage_fn, stacked, x, mesh=mesh,
                          n_microbatches=3)

    def test_requires_pipe_axis(self, rig):
        _, stacked, x = rig
        mesh = mesh_for_devices()  # data-only mesh
        with pytest.raises(ValueError, match="no 'pipe' axis"):
            spmd_pipeline(_stage_fn, stacked, x, mesh=mesh,
                          n_microbatches=4)


class TestBackward:
    def test_grads_match_sequential(self, rig):
        """The backward pipeline falls out of autodiff through the scan —
        grads must equal the sequential program's."""
        stages, stacked, x = rig
        mesh = mesh_for_devices(jax.devices()[:4], pipe=4)

        def loss_pipe(p, b):
            return jnp.sum(
                spmd_pipeline(_stage_fn, p, b, mesh=mesh, n_microbatches=4)
                ** 2
            )

        def loss_seq(plist, b):
            return jnp.sum(_sequential(plist, b) ** 2)

        g_pipe = jax.grad(loss_pipe)(stacked, x)
        g_seq = jax.grad(loss_seq)(stages, x)
        g_seq_stacked = stack_pipeline_stages(g_seq)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4
            ),
            g_pipe, g_seq_stacked,
        )


class TestPerShardDivisibility:
    def test_local_batch_must_divide_microbatches(self, rig):
        """Divisibility is per data shard, not global: batch 8 over
        data=2 gives local batch 4, so n_microbatches=8 must raise a
        clear ValueError, not an opaque trace-time reshape error."""
        _, stacked, x = rig
        mesh = mesh_for_devices(pipe=4)  # pipe=4 × data=2
        with pytest.raises(ValueError, match="per-shard batch"):
            spmd_pipeline(_stage_fn, stacked, x, mesh=mesh,
                          n_microbatches=8)

    def test_stage_count_must_match_pipe_axis(self, rig):
        """4 stacked stages on a pipe=2 mesh must raise, not silently run
        a 2-stage pipeline that ignores stages 1 and 3."""
        _, stacked, x = rig
        mesh = mesh_for_devices(jax.devices()[:2], pipe=2)
        with pytest.raises(ValueError, match="4 stage"):
            spmd_pipeline(_stage_fn, stacked, x, mesh=mesh,
                          n_microbatches=4)


class TestParamPlacement:
    def test_pipeline_param_sharding_places_stage_dim_on_pipe(self):
        from cron_operator_tpu.parallel.pipeline import (
            pipeline_param_sharding,
        )
        from cron_operator_tpu.parallel.mesh import PIPE_AXIS

        mesh = mesh_for_devices(jax.devices()[:4], pipe=4)
        sh = pipeline_param_sharding(
            {"w": jnp.zeros((4, 2)), "b": jnp.zeros((4,))}, mesh)
        assert sh["w"].spec == jax.sharding.PartitionSpec(PIPE_AXIS)

    def test_pipe_param_rejected_by_standard_entrypoints(self):
        from cron_operator_tpu.backends.registry import JobContext
        from cron_operator_tpu.workloads.entrypoints import _mesh

        ctx = JobContext(
            name="p", namespace="default", job={},
            params={"pipe": "2", "platform": "cpu"},
        )
        with pytest.raises(ValueError, match="spmd_pipeline"):
            _mesh(ctx)
