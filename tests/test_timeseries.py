"""Bounded time-series store (telemetry/timeseries.py): append/snapshot
correctness, multi-resolution rollup, ring eviction (the slot overwrite
that IS the eviction pass), horizon exclusion for quiet series, the
max_series bound, the ``/debug/timeline`` JSON surface, and the
``Metrics.instrument`` history mirror (counters → cumulative totals,
gauges → set values, histograms → raw observations, family filtering,
``remove_series`` GC)."""

from __future__ import annotations

import json

import pytest

from cron_operator_tpu.runtime.manager import Metrics
from cron_operator_tpu.telemetry.timeseries import (
    DEFAULT_HISTORY_FAMILIES,
    DEFAULT_RESOLUTIONS,
    TimeSeriesStore,
)


class TestAppendSnapshot:
    def test_bucket_aggregates(self):
        ts = TimeSeriesStore()
        assert ts.append("m", 2.0, ts=100.2)
        assert ts.append("m", 6.0, ts=100.7)
        assert ts.append("m", 1.0, ts=101.1)
        pts = ts.snapshot("m", "1s", now=102.0)
        assert [p["t"] for p in pts] == [100.0, 101.0]
        first = pts[0]
        assert first["count"] == 2
        assert first["sum"] == 8.0
        assert first["min"] == 2.0
        assert first["max"] == 6.0
        assert first["mean"] == 4.0
        assert pts[1]["count"] == 1
        assert ts.points_total == 3

    def test_unknown_series_is_empty(self):
        assert TimeSeriesStore().snapshot("nope") == []

    def test_multi_resolution_rollup(self):
        # One pass of appends lands in every ring at once; the coarse
        # rings aggregate what the fine ring splits across buckets.
        ts = TimeSeriesStore()
        for i in range(60):
            ts.append("m", float(i + 1), ts=1000.0 + i)
        fine = ts.snapshot("m", "1s", now=1059.0)
        assert len(fine) == 60
        assert all(p["count"] == 1 for p in fine)
        mid = ts.snapshot("m", "10s", now=1059.0)
        assert len(mid) == 6
        assert all(p["count"] == 10 for p in mid)
        coarse = ts.snapshot("m", "60s", now=1059.0)
        # 1000..1059 straddles the 960/1020 bucket edge.
        assert len(coarse) == 2
        assert sum(p["count"] for p in coarse) == 60
        assert sum(p["sum"] for p in coarse) == sum(range(1, 61))
        assert max(p["max"] for p in coarse) == 60.0
        assert min(p["min"] for p in coarse) == 1.0

    def test_downsample_mean(self):
        ts = TimeSeriesStore(resolutions=((60.0, 4),))
        for i in range(60):
            ts.append("m", float(i + 1), ts=float(i))
        (pt,) = ts.snapshot("m", "60s", now=59.0)
        assert pt["count"] == 60
        assert pt["sum"] == 1830.0
        assert pt["mean"] == 30.5

    def test_snapshot_limit_keeps_newest(self):
        ts = TimeSeriesStore()
        for i in range(10):
            ts.append("m", 1.0, ts=100.0 + i)
        pts = ts.snapshot("m", "1s", now=109.0, limit=3)
        assert [p["t"] for p in pts] == [107.0, 108.0, 109.0]


class TestRingEviction:
    def test_scrolled_slot_overwritten_in_place(self):
        ts = TimeSeriesStore(resolutions=((1.0, 4),))
        for i in range(4):
            ts.append("m", float(i), ts=float(i))
        assert [p["t"] for p in ts.snapshot("m", now=3.0)] == [
            0.0, 1.0, 2.0, 3.0,
        ]
        # ts=4 maps to slot 0 (4 % 4): bucket 0's aggregates are reset
        # in place — eviction IS the append, no compaction pass.
        ts.append("m", 42.0, ts=4.0)
        pts = ts.snapshot("m", now=4.0)
        assert [p["t"] for p in pts] == [1.0, 2.0, 3.0, 4.0]
        assert pts[-1]["max"] == 42.0

    def test_horizon_excludes_stale_quiet_buckets(self):
        # A series that went quiet must not resurface buckets whose
        # wall-clock window scrolled past the ring horizon, even though
        # no later append overwrote their slots.
        ts = TimeSeriesStore(resolutions=((1.0, 4),))
        ts.append("m", 1.0, ts=0.0)
        assert ts.snapshot("m", now=0.0)
        assert ts.snapshot("m", now=100.0) == []

    def test_max_series_refusal_is_counted(self):
        ts = TimeSeriesStore(max_series=2)
        assert ts.append("a", 1.0, ts=0.0)
        assert ts.append("b", 1.0, ts=0.0)
        assert not ts.append("c", 1.0, ts=0.0)
        assert ts.series_dropped == 1
        # Known series still accept after the cap is hit.
        assert ts.append("a", 2.0, ts=1.0)
        assert ts.series_names() == ["a", "b"]

    def test_invalid_resolutions_rejected(self):
        with pytest.raises(ValueError):
            TimeSeriesStore(resolutions=())
        with pytest.raises(ValueError):
            TimeSeriesStore(resolutions=((0.0, 10),))
        with pytest.raises(ValueError):
            TimeSeriesStore(resolutions=((1.0, 0),))


class TestResolutionsAndRender:
    def test_resolution_names_and_resolve(self):
        ts = TimeSeriesStore()
        assert ts.resolution_names() == ["1s", "10s", "60s"]
        assert ts._resolve_res(None) == DEFAULT_RESOLUTIONS[0]
        assert ts._resolve_res("10s") == (10.0, 360)
        assert ts._resolve_res("10") == (10.0, 360)
        with pytest.raises(KeyError):
            ts._resolve_res("7s")

    def test_render_json_family_and_series_filters(self):
        ts = TimeSeriesStore()
        ts.append('cron_ticks_fired_total{shard="0"}', 1.0, ts=100.0)
        ts.append('cron_ticks_fired_total{shard="1"}', 2.0, ts=100.0)
        ts.append("cron_jobs_pending", 3.0, ts=100.0)
        assert ts.families() == [
            "cron_jobs_pending", "cron_ticks_fired_total",
        ]
        body = json.loads(ts.render_json(
            {"family": ["cron_ticks_fired_total"]}
        ))
        assert set(body["series"]) == {
            'cron_ticks_fired_total{shard="0"}',
            'cron_ticks_fired_total{shard="1"}',
        }
        assert body["res"] == "1s"
        assert body["resolutions"] == ["1s", "10s", "60s"]
        assert body["points_total"] == 3
        assert body["series_dropped"] == 0
        body = json.loads(ts.render_json(
            {"series": ["cron_jobs_pending"], "res": ["60s"]}
        ))
        assert list(body["series"]) == ["cron_jobs_pending"]
        assert body["res"] == "60s"

    def test_render_json_bad_res_is_an_error_body(self):
        body = json.loads(TimeSeriesStore().render_json({"res": ["7s"]}))
        assert "error" in body
        assert "7s" in body["error"]

    def test_render_json_bad_limit_falls_back(self):
        # render_json snapshots against the wall clock, so the sample
        # must be recent to sit inside the ring horizon.
        ts = TimeSeriesStore()
        ts.append("m", 1.0)
        body = json.loads(ts.render_json({"limit": ["bogus"]}))
        assert body["series"]["m"]


class TestMetricsInstrument:
    def test_counter_history_is_cumulative_total(self):
        m, ts = Metrics(), TimeSeriesStore()
        m.instrument(ts, families=["cron_ticks_fired_total"])
        m.inc("cron_ticks_fired_total", 2.0)
        m.inc("cron_ticks_fired_total", 3.0)
        pts = ts.snapshot("cron_ticks_fired_total")
        assert sum(p["count"] for p in pts) == 2
        # History max equals the live counter — the bucket records the
        # new cumulative total, not the per-call delta.
        assert max(p["max"] for p in pts) == m.get(
            "cron_ticks_fired_total"
        ) == 5.0

    def test_gauge_and_histogram_history(self):
        m, ts = Metrics(), TimeSeriesStore()
        m.instrument(ts)  # families=None opts every family in
        m.set("workload_mfu", 0.41)
        m.set("workload_mfu", 0.39)
        pts = ts.snapshot("workload_mfu")
        assert max(p["max"] for p in pts) == 0.41
        assert min(p["min"] for p in pts) == 0.39
        m.observe("cron_schedule_delay_seconds", 1.5)
        m.observe("cron_schedule_delay_seconds", 0.5)
        pts = ts.snapshot("cron_schedule_delay_seconds")
        assert sum(p["count"] for p in pts) == 2
        assert sum(p["sum"] for p in pts) == 2.0

    def test_family_filter_applies_to_labeled_series(self):
        m, ts = Metrics(), TimeSeriesStore()
        m.instrument(ts, families=["fleet_utilization"])
        m.set('fleet_utilization{slice_type="v5e-16"}', 0.75)
        m.set("cron_jobs_pending", 4.0)  # not opted in
        m.inc("audit_records_total")  # not opted in
        assert ts.series_names() == [
            'fleet_utilization{slice_type="v5e-16"}',
        ]

    def test_detach_stops_mirroring(self):
        m, ts = Metrics(), TimeSeriesStore()
        m.instrument(ts)
        m.set("cron_jobs_pending", 1.0)
        m.instrument(None)
        m.set("cron_jobs_pending", 2.0)
        pts = ts.snapshot("cron_jobs_pending")
        assert sum(p["count"] for p in pts) == 1

    def test_default_families_cover_fleet_and_deadline_series(self):
        for fam in ("cron_deadline_hits_total", "cron_deadline_misses_total",
                    "fleet_utilization", "workload_mfu"):
            assert fam in DEFAULT_HISTORY_FAMILIES

    def test_remove_series_gc(self):
        m = Metrics()
        wl = 'workload_tokens_per_s{workload="default/train-abc"}'
        m.set(wl, 123.0)
        assert m.remove_series(wl)
        assert not m.remove_series(wl)  # already gone
        assert wl not in m.render_prometheus()
