"""Deploy-layer checks: generated CRD in sync with the checked-in manifest
(the reference CI's codegen-drift gate, SURVEY.md §4 item 4), operator
manifest sanity, examples loadable and schedulable."""

import pathlib

import yaml

from cron_operator_tpu.api.crd import crd_manifest, render_yaml
from cron_operator_tpu.controller.schedule import parse_standard
from cron_operator_tpu.controller.workload import new_empty_workload
from cron_operator_tpu.api.v1alpha1 import Cron

REPO = pathlib.Path(__file__).resolve().parents[1]


def test_crd_manifest_in_sync():
    on_disk = (REPO / "deploy" / "crds" / "apps.kubedl.io_crons.yaml").read_text()
    assert on_disk == render_yaml(), (
        "deploy/crds drifted from api/crd.py — regenerate with "
        "`python -m cron_operator_tpu.api.crd`"
    )


def test_crd_schema_shape():
    crd = crd_manifest()
    assert crd["metadata"]["name"] == "crons.apps.kubedl.io"
    v = crd["spec"]["versions"][0]
    assert v["subresources"] == {"status": {}}
    props = v["schema"]["openAPIV3Schema"]["properties"]
    spec = props["spec"]
    assert spec["required"] == ["schedule", "template"]
    assert spec["properties"]["concurrencyPolicy"]["enum"] == [
        "Allow", "Forbid", "Replace",
    ]
    workload = spec["properties"]["template"]["properties"]["workload"]
    assert workload["x-kubernetes-preserve-unknown-fields"] is True
    cols = [c["name"] for c in v["additionalPrinterColumns"]]
    assert cols == ["Schedule", "Suspend", "Last Schedule", "Age"]


def test_operator_manifest_parses():
    docs = list(yaml.safe_load_all(
        (REPO / "deploy" / "operator.yaml").read_text()
    ))
    kinds = [d["kind"] for d in docs if d]
    assert "Deployment" in kinds and "ClusterRole" in kinds
    role = next(d for d in docs if d["kind"] == "ClusterRole")
    workload_rule = next(
        r for r in role["rules"] if "kubeflow.org" in r.get("apiGroups", [])
    )
    assert "jaxjobs" in workload_rule["resources"]


def test_examples_parse_and_validate():
    """Every example must parse, carry a valid schedule, and yield a
    workload the reconciler accepts."""
    examples = sorted((REPO / "examples" / "v1alpha1" / "cron").glob("*.yaml"))
    assert len(examples) >= 6
    for path in examples:
        # Multi-document files (e.g. the train+serve pairing) are
        # ordinary kubectl practice; validate every document.
        docs = [d for d in yaml.safe_load_all(path.read_text()) if d]
        assert docs, path.name
        for doc in docs:
            assert doc["kind"] == "Cron", path.name
            cron = Cron.from_dict(doc)
            parse_standard(cron.spec.schedule)  # raises on bad schedule
            workload = new_empty_workload(cron)  # raises on bad template
            assert workload.get("kind"), path.name


class TestKustomizeTree:
    """The second install path (`kubectl apply -k config/default`) —
    reference config/default/kustomization.yaml analog. No kustomize
    binary ships in this image, so validation is structural: every
    kustomization parses, every referenced resource exists and is valid
    YAML with a GVK, and the CRD base is generator-synced."""

    CONFIG = REPO / "config"

    def _kustomization(self, rel):
        path = self.CONFIG / rel / "kustomization.yaml"
        assert path.exists(), f"missing {path}"
        return yaml.safe_load(path.read_text())

    def test_overlays_reference_existing_resources(self):
        for rel in ("crd", "rbac", "manager", "prometheus",
                    "network-policy", "default"):
            k = self._kustomization(rel)
            assert k["kind"] == "Kustomization"
            for res in k.get("resources", []):
                target = (self.CONFIG / rel / res).resolve()
                assert target.exists(), f"{rel}: dangling resource {res}"
                if target.is_file():
                    docs = [
                        d for d in
                        yaml.safe_load_all(target.read_text()) if d
                    ]
                    assert docs, f"{target} is empty"
                    for d in docs:
                        assert d.get("kind"), f"{target}: doc without kind"
                        assert d.get("apiVersion"), (
                            f"{target}: doc without apiVersion"
                        )
                else:
                    assert (target / "kustomization.yaml").exists(), (
                        f"{rel}: {res} is not a kustomization dir"
                    )

    def test_default_overlay_composition(self):
        k = self._kustomization("default")
        assert k["namespace"] == "cron-operator-tpu-system"
        assert k["namePrefix"] == "cron-operator-tpu-"
        assert "../crd" in k["resources"]
        assert "../rbac" in k["resources"]
        assert "../manager" in k["resources"]

    def test_crd_base_in_sync(self):
        on_disk = (
            self.CONFIG / "crd" / "bases" / "apps.kubedl.io_crons.yaml"
        ).read_text()
        assert on_disk == render_yaml(), (
            "config/crd/bases drifted from api/crd.py — regenerate with "
            "`python -m cron_operator_tpu.api.crd`"
        )

    def test_manager_args_match_deploy_manifest(self):
        """Both install paths must start the operator the same way."""
        mgr = None
        for d in yaml.safe_load_all(
            (self.CONFIG / "manager" / "manager.yaml").read_text()
        ):
            if d and d.get("kind") == "Deployment":
                mgr = d
        assert mgr is not None
        args = mgr["spec"]["template"]["spec"]["containers"][0]["args"]
        deploy = None
        for d in yaml.safe_load_all(
            (REPO / "deploy" / "operator.yaml").read_text()
        ):
            if d and d.get("kind") == "Deployment":
                deploy = d
        dargs = deploy["spec"]["template"]["spec"]["containers"][0]["args"]
        # The two install paths intentionally diverge on the metrics
        # posture (reference parity: its kustomize manager serves secure
        # :8443, its chart/plain path pins --metrics-secure=false on
        # :8080); everything else must stay in lockstep.
        metrics = ("--metrics-bind-address", "--metrics-secure")

        def non_metrics(a):
            return [x for x in a if not x.startswith(metrics)]

        assert non_metrics(args) == non_metrics(dargs)
        assert "--metrics-bind-address=:8443" in args  # secure kustomize
        assert "--metrics-secure=false" not in args
        assert "--metrics-bind-address=:8080" in dargs  # plain manifest
        assert "--metrics-secure=false" in dargs
