"""Compiled-schedule cache (``parse_standard_cached``) and bit-scan
``CronSchedule.next`` equivalence against a minute-stepping reference.

The cache is keyed by the spec string: identical specs share ONE compiled
object across every Cron, an edited spec is a new key (instant recompile),
and unparseable specs are never cached so a bad edit keeps raising its
terminal error on every reconcile. The bit-scan rewrite of ``next`` must
be observationally identical to stepping one minute at a time through the
masks — verified here over a seeded randomized spec sweep that includes
the vixie dom/dow OR rule, names, steps and ``@every``.
"""

import random
from datetime import datetime, timedelta, timezone

import pytest

from cron_operator_tpu.controller.schedule import (
    CronSchedule,
    EverySchedule,
    parse_standard,
    parse_standard_cached,
)


def utc(*args):
    return datetime(*args, tzinfo=timezone.utc)


class TestCompiledScheduleCache:
    def test_identical_specs_share_one_compiled_object(self):
        a = parse_standard_cached("*/5 9-17 * * MON-FRI")
        b = parse_standard_cached("*/5 9-17 * * MON-FRI")
        assert a is b

    def test_spec_change_recompiles(self):
        a = parse_standard_cached("0 * * * *")
        b = parse_standard_cached("1 * * * *")
        assert a is not b
        assert a.next(utc(2026, 1, 1)) != b.next(utc(2026, 1, 1))

    def test_cached_matches_uncached(self):
        for expr in ["*/7 * * * *", "@hourly", "@every 90s",
                     "15,45 */2 1-15 JAN,jul *"]:
            t = utc(2026, 3, 14, 1, 59)
            assert parse_standard_cached(expr).next(t) == \
                parse_standard(expr).next(t)

    def test_unparseable_spec_errors_every_time(self):
        # lru_cache must not memoize the exception: an unparseable edit
        # keeps surfacing its terminal error on every reconcile.
        for _ in range(3):
            with pytest.raises(ValueError):
                parse_standard_cached("61 * * * *")

    def test_every_schedule_cached_too(self):
        a = parse_standard_cached("@every 1h30m")
        assert isinstance(a, EverySchedule)
        assert parse_standard_cached("@every 1h30m") is a


# ---- bit-scan vs minute-stepping equivalence ----------------------------


def _next_by_stepping(sched: CronSchedule, after: datetime) -> datetime:
    """Reference implementation: advance one minute at a time and test
    every candidate against the compiled masks directly."""
    t = after.replace(second=0, microsecond=0) + timedelta(minutes=1)
    limit = after + timedelta(days=366 * 2)
    while t <= limit:
        if (
            sched.month & (1 << t.month)
            and sched._day_matches(t)
            and sched.hour & (1 << t.hour)
            and sched.minute & (1 << t.minute)
        ):
            return t
        t += timedelta(minutes=1)
    raise AssertionError("no activation within 2 years")


def _random_field(rng, lo, hi, names=None):
    kind = rng.randrange(5)
    if kind == 0:
        return "*"
    if kind == 1:
        return f"*/{rng.randint(2, 20)}"
    if kind == 2:
        a = rng.randint(lo, hi - 1)
        b = rng.randint(a, hi)
        expr = f"{a}-{b}"
        if rng.random() < 0.5:
            expr += f"/{rng.randint(1, 5)}"
        return expr
    if kind == 3 and names:
        return rng.choice(list(names)).upper()
    return ",".join(
        str(rng.randint(lo, hi)) for _ in range(rng.randint(1, 3))
    )


class TestBitScanEquivalence:
    @pytest.mark.parametrize("seed", range(8))
    def test_randomized_specs_match_stepping(self, seed):
        from cron_operator_tpu.controller.schedule import (
            DOW_NAMES,
            MONTH_NAMES,
        )

        rng = random.Random(seed)
        for _ in range(25):
            expr = " ".join([
                _random_field(rng, 0, 59),
                _random_field(rng, 0, 23),
                _random_field(rng, 1, 28),  # stay clear of 29-31
                _random_field(rng, 1, 12, MONTH_NAMES),
                _random_field(rng, 0, 6, DOW_NAMES),
            ])
            try:
                sched = parse_standard(expr)
            except ValueError:
                continue
            after = utc(2026, 1, 1) + timedelta(
                minutes=rng.randrange(0, 400 * 24 * 60),
                seconds=rng.randrange(0, 60),
            )
            assert sched.next(after) == _next_by_stepping(sched, after), (
                f"spec {expr!r} after {after}"
            )

    def test_vixie_dom_dow_or_rule(self):
        # Both restricted: a time matching EITHER field fires. Feb 2026:
        # the 13th is a Friday; "0 0 1 * FRI" must hit Feb 1 (dom) then
        # Feb 6 (dow) — never require both.
        sched = parse_standard("0 0 1 * FRI")
        t = sched.next(utc(2026, 1, 31, 12, 0))
        assert t == utc(2026, 2, 1)
        assert sched.next(t) == utc(2026, 2, 6)

    def test_sparse_schedule_jumps_straight_to_activation(self):
        assert parse_standard("30 4 * * *").next(
            utc(2026, 6, 1, 4, 31)
        ) == utc(2026, 6, 2, 4, 30)

    def test_every_duration_unchanged(self):
        sched = parse_standard("@every 2h")
        assert sched.next(utc(2026, 1, 1, 1, 2, 3)) == utc(2026, 1, 1, 3, 2, 3)
