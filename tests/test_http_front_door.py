"""Production HTTP front door: shared-encode watch fan-out, APF
admission at the wire, group-commit durable writes, delegated bearer
auth, and RFC 7386 merge-patch conformance.

The fan-out assertions here are the dedicated encode-count guard for the
one-encode-per-event contract (the bench measures the speedup; this
pins the mechanism): N watchers receiving E events must cost exactly E
JSON encodes at the hub, never N×E.
"""

import http.client
import json
import socket
import threading
import time

import pytest

from cron_operator_tpu.runtime import apiserver_http as front
from cron_operator_tpu.runtime.apf import FairQueueAdmission, LevelConfig
from cron_operator_tpu.runtime.apiserver_http import (
    HTTPAPIServer,
    _merge_patch,
    _WatchConn,
)
from cron_operator_tpu.runtime.authfilter import (
    ScrapeAuthenticator,
    StaticTokenReviewer,
)
from cron_operator_tpu.runtime.kube import APIServer, WatchEvent
from cron_operator_tpu.runtime.manager import Metrics
from cron_operator_tpu.runtime.persistence import Persistence

TOKEN = "front-door-token"
CRON_AV = "apps.kubedl.io/v1alpha1"
WATCH_PATH = (f"/apis/{CRON_AV}/namespaces/default/crons"
              "?watch=true&resourceVersion=0")


def make_cron(name, namespace="default", labels=None):
    meta = {"name": name, "namespace": namespace}
    if labels:
        meta["labels"] = labels
    return {"apiVersion": CRON_AV, "kind": "Cron", "metadata": meta,
            "spec": {"schedule": "@every 1h", "template": {"workload": {
                "apiVersion": "kubeflow.org/v1", "kind": "JAXJob",
                "spec": {}}}}}


def wait_for(fn, timeout=10.0, message="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        got = fn()
        if got:
            return got
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {message}")


@pytest.fixture
def server():
    srv = HTTPAPIServer(token=TOKEN)
    srv.start()
    yield srv
    srv.stop()


class WatchStream:
    """Raw chunked-watch consumer (http.client decodes the chunking;
    each frame is one JSON line)."""

    def __init__(self, srv, path=WATCH_PATH, token=TOKEN):
        host, port = srv._server.server_address[0], srv.port
        self.conn = http.client.HTTPConnection(host, port, timeout=30)
        headers = {"Authorization": f"Bearer {token}"} if token else {}
        self.conn.request("GET", path, headers=headers)
        self.resp = self.conn.getresponse()
        self.status = self.resp.status
        self.events = []
        self.done = threading.Event()
        self._t = threading.Thread(target=self._pump, daemon=True)
        self._t.start()

    def _pump(self):
        try:
            for raw in self.resp:
                if raw.strip():
                    self.events.append(json.loads(raw))
        except Exception:
            pass
        finally:
            self.done.set()

    def of_type(self, ev_type):
        return [e for e in self.events if e.get("type") == ev_type]

    def close(self):
        # Shut the socket down first: the pump thread sits blocked in a
        # buffered readline holding the reader lock, and a plain
        # conn.close() would block on that lock until the next bookmark
        # frame releases it. EOF unblocks the pump immediately.
        try:
            sock = self.conn.sock
            if sock is not None:
                sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self.done.wait(5.0)
        try:
            self.conn.close()
        except OSError:
            pass


class TestSharedEncodeFanOut:
    def test_encode_once_across_watchers(self, server):
        """8 watchers × 5 events = 40 frames delivered but exactly 5
        JSON encodes — the old path paid deepcopy+dumps per watcher."""
        streams = [WatchStream(server) for _ in range(8)]
        try:
            wait_for(lambda: server.hub._nconns == 8, message="8 streams")
            for i in range(5):
                server.api.create(make_cron(f"fan-{i}"))
            wait_for(
                lambda: all(len(s.of_type("ADDED")) == 5 for s in streams),
                message="all watchers saw all events",
            )
            assert server.hub.encodes == 5
            assert server.hub.frames_sent == 40
            # every stream saw identical payloads, in order
            names = [[e["object"]["metadata"]["name"]
                      for e in s.of_type("ADDED")] for s in streams]
            assert all(n == [f"fan-{i}" for i in range(5)] for n in names)
        finally:
            for s in streams:
                s.close()

    def test_plain_http_streams_run_on_selector_loop(self, server):
        streams = [WatchStream(server) for _ in range(3)]
        try:
            wait_for(lambda: server.hub._nconns == 3, message="3 streams")
            assert server.hub._loop_thread is not None
            assert server.hub._loop_thread.is_alive()
        finally:
            for s in streams:
                s.close()

    def test_watch_connection_gauge(self):
        m = Metrics()
        srv = HTTPAPIServer(token=TOKEN, metrics=m)
        srv.start()
        try:
            s = WatchStream(srv)
            wait_for(lambda: m.gauge("http_watch_connections") == 1,
                     message="gauge up")
            s.close()
            wait_for(lambda: m.gauge("http_watch_connections") == 0,
                     message="gauge back down")
        finally:
            srv.stop()

    def test_bookmarks_flow_on_idle_streams(self, server, monkeypatch):
        monkeypatch.setattr(front, "BOOKMARK_INTERVAL_S", 0.2)
        s = WatchStream(server)
        try:
            wait_for(lambda: s.of_type("BOOKMARK"), timeout=5.0,
                     message="bookmark on idle stream")
            bm = s.of_type("BOOKMARK")[0]
            assert bm["object"]["kind"] == "Cron"
            assert "resourceVersion" in bm["object"]["metadata"]
        finally:
            s.close()


class TestWatchFiltering:
    def test_label_selector_on_watch(self, server):
        path = (f"/apis/{CRON_AV}/namespaces/default/crons"
                "?watch=true&labelSelector=team%3Dml")
        s = WatchStream(server, path=path)
        try:
            wait_for(lambda: server.hub._nconns == 1, message="stream up")
            server.api.create(make_cron("ml-cron", labels={"team": "ml"}))
            server.api.create(make_cron("infra-cron",
                                        labels={"team": "infra"}))
            server.api.create(make_cron("bare-cron"))
            wait_for(lambda: s.of_type("ADDED"), message="selected event")
            time.sleep(0.3)  # would-be leak window for the other two
            names = [e["object"]["metadata"]["name"]
                     for e in s.of_type("ADDED")]
            assert names == ["ml-cron"]
        finally:
            s.close()

    def test_namespace_prefilter_on_watch(self, server):
        s = WatchStream(server)  # namespace=default
        try:
            wait_for(lambda: server.hub._nconns == 1, message="stream up")
            server.api.create(make_cron("other-ns", namespace="prod"))
            server.api.create(make_cron("mine"))
            wait_for(lambda: s.of_type("ADDED"), message="event")
            time.sleep(0.2)
            names = [e["object"]["metadata"]["name"]
                     for e in s.of_type("ADDED")]
            assert names == ["mine"]
        finally:
            s.close()

    def test_label_selector_list_routed_to_index(self, server):
        server.api.create(make_cron("a", labels={"team": "ml"}))
        server.api.create(make_cron("b", labels={"team": "infra"}))
        conn = http.client.HTTPConnection(
            server._server.server_address[0], server.port, timeout=10)
        conn.request(
            "GET",
            f"/apis/{CRON_AV}/namespaces/default/crons"
            "?labelSelector=team%3Dml",
            headers={"Authorization": f"Bearer {TOKEN}"},
        )
        resp = conn.getresponse()
        body = json.loads(resp.read())
        conn.close()
        assert resp.status == 200
        assert [c["metadata"]["name"] for c in body["items"]] == ["a"]


class TestHubMechanics:
    """Hub-level behavior that real sockets can't force deterministically:
    latest-wins coalescing, overflow drop, mid-stream expiry."""

    def _conn(self, server, **kw):
        conn = _WatchConn(
            CRON_AV, "Cron", "default", None, mode="thread",
            cv=threading.Condition(server.hub._lock), **kw)
        assert server.hub.attach(conn, 0) is False
        return conn

    def _publish(self, server, ev_type, name, rv):
        server.hub.publish(WatchEvent(type=ev_type, object={
            "apiVersion": CRON_AV, "kind": "Cron",
            "metadata": {"name": name, "namespace": "default",
                         "resourceVersion": str(rv)},
        }))

    def test_latest_wins_coalescing(self, server):
        conn = self._conn(server)
        try:
            self._publish(server, "ADDED", "obj", 1)
            self._publish(server, "MODIFIED", "obj", 2)
            self._publish(server, "MODIFIED", "obj", 3)
            self._publish(server, "MODIFIED", "obj", 4)
            with server.hub._lock:
                assert len(conn.pending) == 2  # ADDED + one MODIFIED slot
                data = server.hub._pop_frames_locked(conn)
            frames = [json.loads(line) for line in data.split(b"\r\n")
                      if line.startswith(b"{")]
            assert [f["type"] for f in frames] == ["ADDED", "MODIFIED"]
            # the queued MODIFIED was overwritten in place with the newest
            assert frames[1]["object"]["metadata"]["resourceVersion"] == "4"
            assert server.hub.coalesced == 2
        finally:
            server.hub.detach(conn)

    def test_slow_consumer_overflows_and_drops(self, server):
        conn = self._conn(server, max_pending=2)
        try:
            for i in range(4):
                self._publish(server, "ADDED", f"o{i}", i + 1)
            assert conn.overflowed
            assert server.hub.dropped == 1
            with server.hub._lock:
                state = server.hub._tick_locked(conn, time.monotonic())
            assert state == "overflow"
        finally:
            server.hub.detach(conn)

    def test_idle_stream_expires_when_ring_evicts_past_horizon(self, server):
        conn = self._conn(server)
        try:
            with server.hub._cond:
                server.hub._events.clear()
                server.hub._oldest_evicted_rv = 10_000_000
                server.hub._evicted_by_kind[(CRON_AV, "Cron")] = 10_000_000
            with server.hub._lock:
                state = server.hub._tick_locked(conn, time.monotonic())
            assert state == "expired"
        finally:
            server.hub.detach(conn)

    def test_quiet_kind_watcher_survives_ring_churn(self, server):
        """The horizon advances while a stream is idle, so heavy traffic
        on OTHER kinds must not 410 a quiet kind's watcher."""
        conn = self._conn(server)
        try:
            with server.hub._lock:
                server.hub._tick_locked(conn, time.monotonic())
            for i in range(front.WATCH_BUFFER + 50):
                server.hub.publish(WatchEvent(type="ADDED", object={
                    "apiVersion": "v1", "kind": "Pod",
                    "metadata": {"name": f"p{i}", "namespace": "default",
                                 "resourceVersion": str(i + 1)},
                }))
            with server.hub._lock:
                server.hub._pop_frames_locked(conn)  # nothing pending
                state = server.hub._tick_locked(conn, time.monotonic())
            assert state == "ok"
        finally:
            server.hub.detach(conn)


class TestWatch410:
    def test_watch_from_evicted_rv_gets_410_and_stream_ends(self, server):
        server.api.create(make_cron("seed"))
        with server.hub._cond:
            server.hub._events.clear()
            server.hub._oldest_evicted_rv = 10_000_000
        path = (f"/apis/{CRON_AV}/namespaces/default/crons"
                "?watch=true&resourceVersion=5")
        s = WatchStream(server, path=path)
        try:
            assert s.done.wait(5.0), "410 stream must terminate"
            assert len(s.events) == 1
            err = s.events[0]
            assert err["type"] == "ERROR"
            assert err["object"]["code"] == 410
            assert err["object"]["reason"] == "Expired"
        finally:
            s.close()

    def test_client_relists_after_410(self, server):
        """The production client path: ExpiredWatchError → re-list →
        objects created after recovery still arrive (tests/test_e2e_http
        drives the same loop through the reconciler)."""
        from cron_operator_tpu.api.scheme import GVK_CRON, default_scheme
        from cron_operator_tpu.runtime.cluster import (
            ClusterAPIServer,
            ClusterConfig,
        )

        capi = ClusterAPIServer(
            ClusterConfig(server.url, token=TOKEN), scheme=default_scheme())
        seen = []
        capi.add_watcher(lambda ev: seen.append(ev.object["metadata"]["name"]))
        try:
            capi.start_watches([GVK_CRON])
            time.sleep(0.3)
            capi.create(make_cron("pre-410"))
            wait_for(lambda: "pre-410" in seen, message="pre-410 event")
            with server.hub._cond:
                server.hub._events.clear()
                server.hub._oldest_evicted_rv = 10_000_000
                server.hub._cond.notify_all()
            time.sleep(0.3)
            capi.create(make_cron("post-410"))
            wait_for(lambda: "post-410" in seen, timeout=15.0,
                     message="post-recovery event")
        finally:
            capi.stop()


class TestMergePatchRFC7386:
    def test_top_level_null_deletes_key(self):
        assert _merge_patch({"a": 1, "b": 2}, {"a": None}) == {"b": 2}

    def test_null_for_absent_key_is_noop(self):
        assert _merge_patch({"b": 2}, {"a": None}) == {"b": 2}

    def test_arrays_replaced_wholesale(self):
        out = _merge_patch({"l": [1, 2, 3], "keep": True}, {"l": [9]})
        assert out == {"l": [9], "keep": True}

    def test_nested_null_deletes_nested_key(self):
        out = _merge_patch({"m": {"x": 1, "y": 2}}, {"m": {"x": None}})
        assert out == {"m": {"y": 2}}

    def test_scalar_replaces_object_and_vice_versa(self):
        assert _merge_patch({"m": {"x": 1}}, {"m": 7}) == {"m": 7}
        assert _merge_patch({"m": 7}, {"m": {"x": 1}}) == {"m": {"x": 1}}

    def test_rfc_appendix_example(self):
        # RFC 7386 §3 example, abridged
        target = {"title": "Goodbye!",
                  "author": {"givenName": "John", "familyName": "Doe"},
                  "tags": ["example", "sample"], "content": "This will be unchanged"}
        patch = {"title": "Hello!", "phoneNumber": "+01-123-456-7890",
                 "author": {"familyName": None}, "tags": ["example"]}
        assert _merge_patch(target, patch) == {
            "title": "Hello!", "author": {"givenName": "John"},
            "tags": ["example"], "content": "This will be unchanged",
            "phoneNumber": "+01-123-456-7890",
        }

    def test_null_deletion_over_http(self, server):
        cron = make_cron("patch-me", labels={"drop": "me", "keep": "yes"})
        server.api.create(cron)
        conn = http.client.HTTPConnection(
            server._server.server_address[0], server.port, timeout=10)
        conn.request(
            "PATCH",
            f"/apis/{CRON_AV}/namespaces/default/crons/patch-me",
            body=json.dumps(
                {"metadata": {"labels": {"drop": None}}}).encode(),
            headers={"Authorization": f"Bearer {TOKEN}",
                     "Content-Type": "application/merge-patch+json"},
        )
        resp = conn.getresponse()
        body = json.loads(resp.read())
        conn.close()
        assert resp.status == 200
        assert body["metadata"]["labels"] == {"keep": "yes"}


class TestAdmissionAtTheWire:
    def _get(self, srv, path, token=TOKEN):
        conn = http.client.HTTPConnection(
            srv._server.server_address[0], srv.port, timeout=10)
        headers = {"Authorization": f"Bearer {token}"} if token else {}
        conn.request("GET", path, headers=headers)
        resp = conn.getresponse()
        body = resp.read()
        headers_out = dict(resp.getheaders())
        conn.close()
        return resp.status, body, headers_out

    def test_saturated_level_answers_429_with_retry_after(self):
        admission = FairQueueAdmission(levels={"workload": LevelConfig(
            seats=1, queue_depth=1, max_queued=1, queue_timeout_s=0.05)})
        srv = HTTPAPIServer(token=TOKEN, admission=admission)
        srv.start()
        hold = admission.acquire("workload", "hog")
        try:
            status, body, headers = self._get(
                srv, f"/apis/{CRON_AV}/namespaces/default/crons/missing")
            assert status == 429
            assert int(headers["Retry-After"]) >= 1
            assert json.loads(body)["reason"] == "TooManyRequests"
        finally:
            hold.release()
            srv.stop()

    def test_seat_released_after_normal_request(self):
        admission = FairQueueAdmission(levels={"workload": LevelConfig(
            seats=1, queue_depth=1, max_queued=1, queue_timeout_s=0.05)})
        srv = HTTPAPIServer(token=TOKEN, admission=admission)
        srv.start()
        try:
            for _ in range(3):  # would deadlock if seats leaked
                status, _, _ = self._get(
                    srv, f"/apis/{CRON_AV}/namespaces/default/crons/nope")
                assert status == 404
            # The seat is released in _dispatch's finally, a few µs
            # AFTER the response bytes reach the client — poll rather
            # than race the handler thread's tail.
            wait_for(
                lambda: admission.snapshot()["workload"]["in_flight"] == 0,
                message="admission seat released",
            )
        finally:
            srv.stop()

    def test_established_watch_gives_seat_back(self, monkeypatch):
        admission = FairQueueAdmission(levels={"workload": LevelConfig(
            seats=1, queue_depth=4, max_queued=8, queue_timeout_s=0.5)})
        srv = HTTPAPIServer(token=TOKEN, admission=admission)
        srv.start()
        s = None
        try:
            s = WatchStream(srv)
            wait_for(lambda: srv.hub._nconns == 1, message="stream up")
            # the long-lived stream must not pin the only seat
            wait_for(lambda: admission.snapshot()["workload"]["in_flight"] == 0,
                     message="watch seat returned")
            status, _, _ = self._get(
                srv, f"/apis/{CRON_AV}/namespaces/default/crons/nope")
            assert status == 404
        finally:
            if s is not None:
                s.close()
            srv.stop()

    def test_request_metrics_emitted(self):
        m = Metrics()
        srv = HTTPAPIServer(token=TOKEN, metrics=m)
        srv.start()
        try:
            status, _, _ = self._get(
                srv, f"/apis/{CRON_AV}/namespaces/default/crons")
            assert status == 200
            # the handler observes the request AFTER flushing the
            # response, so the counter can trail the client by a moment
            wait_for(
                lambda: m.get(
                    'http_requests_total{code="200",verb="GET"}') == 1,
                timeout=5.0, message="request counter")
            hist = m.histogram('http_request_seconds{verb="GET"}')
            assert hist is not None and hist["count"] == 1
        finally:
            srv.stop()

    def test_admission_disabled_with_false(self):
        srv = HTTPAPIServer(token=TOKEN, admission=False)
        srv.start()
        try:
            assert srv.apf is None
            status, _, _ = self._get(
                srv, f"/apis/{CRON_AV}/namespaces/default/crons")
            assert status == 200
        finally:
            srv.stop()


class TestDelegatedAuth:
    def test_identify_and_counters(self):
        m = Metrics()
        auth = ScrapeAuthenticator(
            StaticTokenReviewer({"tok": "alice"}), path="/apis")
        auth.instrument(m)
        assert auth.identify("Bearer tok") == "alice"
        assert m.get("scrape_auth_cache_misses_total") == 1
        assert auth.identify("Bearer tok") == "alice"
        assert m.get("scrape_auth_cache_hits_total") == 1
        assert m.get("scrape_auth_cache_misses_total") == 1
        # allow() keeps its strict-bool contract on the shared path
        assert auth.allow("Bearer tok") is True
        assert auth.allow("Bearer forged") is False
        assert m.get("scrape_auth_denials_total") == 1
        # negative outcome is cached: the repeat deny is a hit, no review
        assert auth.allow("Bearer forged") is False
        assert m.get("scrape_auth_cache_hits_total") >= 3
        assert m.get("scrape_auth_denials_total") == 2
        # malformed headers deny without burning a cache miss
        misses = m.get("scrape_auth_cache_misses_total")
        assert auth.allow(None) is False
        assert auth.allow("Basic Zm9v") is False
        assert m.get("scrape_auth_cache_misses_total") == misses
        assert m.get("scrape_auth_denials_total") == 4

    def test_front_door_401_for_bad_token(self, server):
        conn = http.client.HTTPConnection(
            server._server.server_address[0], server.port, timeout=10)
        conn.request("GET", f"/apis/{CRON_AV}/namespaces/default/crons",
                     headers={"Authorization": "Bearer wrong"})
        resp = conn.getresponse()
        resp.read()
        conn.close()
        assert resp.status == 401

    def test_tenant_tokens_map_to_identities(self):
        srv = HTTPAPIServer(tokens={"t-a": "tenant-a", "t-b": "tenant-b"})
        try:
            assert srv.authn.identify("Bearer t-a") == "tenant-a"
            assert srv.authn.identify("Bearer t-b") == "tenant-b"
            assert srv.authn.identify("Bearer nope") is None
        finally:
            srv.stop()


class TestGroupCommitDurability:
    def test_concurrent_waiters_share_fsyncs(self, tmp_path):
        wal = Persistence(str(tmp_path), fsync_every=10_000,
                          flush_interval_s=0)
        wal.open()
        errors = []

        def writer(i):
            try:
                for j in range(5):
                    wal.append_put("create", {
                        "apiVersion": "v1", "kind": "Pod",
                        "metadata": {"name": f"w{i}-{j}",
                                     "namespace": "default",
                                     # rv 0 would be skipped on replay as
                                     # <= the empty snapshot's rv
                                     "resourceVersion": str(i * 100 + j + 1)},
                    })
                    assert wal.wait_durable(timeout=10.0)
            except Exception as exc:  # pragma: no cover
                errors.append(exc)

        threads = [threading.Thread(target=writer, args=(i,))
                   for i in range(16)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30.0)
        assert not errors
        assert wal.records_appended == 80
        assert wal.durable_seq == 80
        # group commit: 80 durability barriers, far fewer fsyncs
        assert wal.fsyncs < 80
        wal.close()
        state = Persistence(str(tmp_path)).recover()
        assert state.wal_records_replayed == 80

    def test_wait_durable_trivial_when_caught_up(self, tmp_path):
        wal = Persistence(str(tmp_path), flush_interval_s=0)
        wal.open()
        assert wal.wait_durable() is True  # nothing appended
        wal.append_put("create", {"metadata": {"resourceVersion": "1"}})
        assert wal.wait_durable() is True
        before = wal.fsyncs
        assert wal.wait_durable() is True  # already durable: no new fsync
        assert wal.fsyncs == before
        wal.close()

    def test_wait_durable_false_on_dead_layer(self, tmp_path):
        wal = Persistence(str(tmp_path), flush_interval_s=0)
        wal.open()
        wal.append_put("create", {"metadata": {"resourceVersion": "1"}})
        wal.kill()
        assert wal.wait_durable(timeout=0.2) is False

    def test_store_barrier_without_wal_is_trivially_durable(self):
        api = APIServer()
        assert api.wait_durable() is True

    def test_store_barrier_with_wal(self, tmp_path):
        api = APIServer()
        wal = Persistence(str(tmp_path), fsync_every=10_000,
                          flush_interval_s=0)
        wal.open()
        api.attach_persistence(wal)
        api.create(make_cron("durable"))
        assert api.wait_durable() is True
        assert wal.durable_seq == wal.records_appended == 1
        wal.close()

    def test_http_write_blocks_on_group_commit(self, tmp_path):
        api = APIServer()
        wal = Persistence(str(tmp_path), fsync_every=10_000,
                          flush_interval_s=0)
        wal.open()
        api.attach_persistence(wal)
        srv = HTTPAPIServer(api=api, token=TOKEN)
        srv.start()
        try:
            conn = http.client.HTTPConnection(
                srv._server.server_address[0], srv.port, timeout=10)
            conn.request(
                "POST", f"/apis/{CRON_AV}/namespaces/default/crons",
                body=json.dumps(make_cron("over-http")).encode(),
                headers={"Authorization": f"Bearer {TOKEN}"},
            )
            resp = conn.getresponse()
            resp.read()
            conn.close()
            assert resp.status == 201
            # the 201 means ON DISK, not just committed in memory
            assert wal.durable_seq == wal.records_appended >= 1
        finally:
            srv.stop()
            wal.close()
