"""Real-apiserver e2e tier (VERDICT r2 #6) — the envtest analog.

The reference never tests against request fakes: envtest boots a real
etcd+apiserver (``suite_test.go:72-79``) and kind e2e installs the chart.
No kube-apiserver binary exists in this image, so these tests run the FULL
production stack over real sockets instead:

    ClusterAPIServer (stdlib REST/auth/chunked-watch client)
        ⇅ HTTP on 127.0.0.1
    HTTPAPIServer (kube REST dialect over the embedded store)

and drive the operator end-to-end: apply a Cron CR → reconciler POSTs the
workload (with TPU admission) → status/history sync → history GC → Replace
semantics — closing the e2e gap the reference itself left open
(``test/e2e/e2e_test.go:281-289`` TODO).
"""

import time

import pytest

from cron_operator_tpu.api.scheme import GVK_CRON, default_scheme
from cron_operator_tpu.controller import CronReconciler
from cron_operator_tpu.runtime import Manager
from cron_operator_tpu.runtime.apiserver_http import HTTPAPIServer
from cron_operator_tpu.runtime.cluster import ClusterAPIServer, ClusterConfig
from cron_operator_tpu.runtime.kube import (
    AlreadyExistsError,
    ApiError,
    NotFoundError,
)

TOKEN = "e2e-bearer-token"


@pytest.fixture
def server():
    srv = HTTPAPIServer(token=TOKEN)
    srv.start()
    yield srv
    srv.stop()


@pytest.fixture
def client(server):
    capi = ClusterAPIServer(
        ClusterConfig(server.url, token=TOKEN), scheme=default_scheme()
    )
    yield capi
    capi.stop()


def make_cron(name="e2e", schedule="@every 1s", policy=None, history=None,
              tpu=True, sim="50ms"):
    ann = {"tpu.kubedl.io/simulate-duration": sim}
    if tpu:
        ann.update({
            "tpu.kubedl.io/accelerator": "v5e",
            "tpu.kubedl.io/topology": "2x2",
        })
    spec = {
        "schedule": schedule,
        "template": {"workload": {
            "apiVersion": "kubeflow.org/v1", "kind": "JAXJob",
            "metadata": {"annotations": ann},
            "spec": {"replicaSpecs": {"Worker": {"replicas": 1}}},
        }},
    }
    if policy:
        spec["concurrencyPolicy"] = policy
    if history is not None:
        spec["historyLimit"] = history
    return {
        "apiVersion": "apps.kubedl.io/v1alpha1", "kind": "Cron",
        "metadata": {"name": name, "namespace": "default"},
        "spec": spec,
    }


def wait_for(fn, timeout=10.0, interval=0.1, message="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        got = fn()
        if got:
            return got
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {message}")


class TestProtocol:
    """Client↔server protocol reality: auth, errors, subresources."""

    def test_bearer_auth_enforced(self, server):
        bad = ClusterAPIServer(
            ClusterConfig(server.url, token="wrong"),
            scheme=default_scheme(),
        )
        with pytest.raises(ApiError, match="401"):
            bad.create(make_cron())
        bad.stop()

    def test_crud_roundtrip_with_server_side_fields(self, client):
        created = client.create(make_cron())
        assert created["metadata"]["uid"]
        assert created["metadata"]["resourceVersion"]
        assert created["metadata"]["creationTimestamp"]
        got = client.get("apps.kubedl.io/v1alpha1", "Cron", "default", "e2e")
        assert got["spec"]["schedule"] == "@every 1s"
        with pytest.raises(AlreadyExistsError):
            client.create(make_cron())
        client.delete("apps.kubedl.io/v1alpha1", "Cron", "default", "e2e")
        with pytest.raises(NotFoundError):
            client.get("apps.kubedl.io/v1alpha1", "Cron", "default", "e2e")

    def test_status_subresource_merge_patch(self, client):
        client.create(make_cron())
        client.patch_status(
            "apps.kubedl.io/v1alpha1", "Cron", "default", "e2e",
            {"lastScheduleTime": "2026-07-29T00:00:00Z"},
        )
        got = client.get("apps.kubedl.io/v1alpha1", "Cron", "default", "e2e")
        assert got["status"]["lastScheduleTime"] == "2026-07-29T00:00:00Z"
        # spec untouched by status writes
        assert got["spec"]["schedule"] == "@every 1s"

    def test_label_selector_list(self, client):
        c1 = make_cron("a")
        c1["metadata"]["labels"] = {"team": "ml"}
        c2 = make_cron("b")
        c2["metadata"]["labels"] = {"team": "infra"}
        client.create(c1)
        client.create(c2)
        ml = client.list("apps.kubedl.io/v1alpha1", "Cron", "default",
                         label_selector={"team": "ml"})
        assert [c["metadata"]["name"] for c in ml] == ["a"]

    def test_cascading_delete_via_owner_refs(self, client):
        owner = client.create(make_cron())
        client.create({
            "apiVersion": "kubeflow.org/v1", "kind": "JAXJob",
            "metadata": {
                "name": "child", "namespace": "default",
                "ownerReferences": [{
                    "apiVersion": "apps.kubedl.io/v1alpha1", "kind": "Cron",
                    "name": "e2e", "uid": owner["metadata"]["uid"],
                    "controller": True,
                }],
            },
            "spec": {},
        })
        client.delete("apps.kubedl.io/v1alpha1", "Cron", "default", "e2e",
                      propagation="Background")
        assert client.try_get("kubeflow.org/v1", "JAXJob", "default",
                              "child") is None

    def test_events_recorded_as_objects(self, client):
        cron = client.create(make_cron())
        client.record_event(cron, "Warning", "E2ECheck", "hello")
        events = client.list("v1", "Event", "default")
        assert any(e.get("reason") == "E2ECheck" for e in events)


class TestWatchStream:
    def test_watch_delivers_adds_and_deletes(self, server, client):
        seen = []
        client.add_watcher(lambda ev: seen.append((ev.type,
                                                   ev.object.get("kind"))))
        client.start_watches([GVK_CRON])
        time.sleep(0.3)  # initial LIST settles
        client.create(make_cron())
        wait_for(lambda: ("ADDED", "Cron") in seen, message="ADDED event")
        client.delete("apps.kubedl.io/v1alpha1", "Cron", "default", "e2e")
        wait_for(lambda: ("DELETED", "Cron") in seen, message="DELETED event")

    def test_watch_survives_410_expiry_with_relist(self, server, client):
        """Force the ring buffer past the client's resumption point; the
        client must see the 410 ERROR and recover by re-listing."""
        seen = []
        client.add_watcher(
            lambda ev: seen.append(ev.object["metadata"]["name"])
        )
        client.start_watches([GVK_CRON])
        time.sleep(0.3)
        client.create(make_cron("before-expiry"))
        wait_for(lambda: "before-expiry" in seen, message="pre-expiry event")
        # Evict history out from under any resumption rv.
        server.hub._oldest_evicted_rv = 10_000_000
        with server.hub._cond:
            server.hub._events.clear()
            server.hub._cond.notify_all()
        # The stream gets ERROR/410 → watch loop re-lists; objects created
        # after recovery must still arrive.
        time.sleep(0.5)
        client.create(make_cron("after-expiry"))
        wait_for(lambda: "after-expiry" in seen, timeout=15.0,
                 message="post-recovery event")


class TestOperatorE2E:
    """The full production loop over the wire."""

    def _start_operator(self, client):
        mgr = Manager(client, max_concurrent_reconciles=4)
        rec = CronReconciler(client)
        mgr.add_controller("cron", rec.reconcile, for_gvk=GVK_CRON,
                           owns=default_scheme().workload_kinds())
        mgr.start()
        client.start_watches([GVK_CRON] + default_scheme().workload_kinds())
        return mgr

    def test_cron_cr_to_workload_with_tpu_admission(self, server, client):
        mgr = self._start_operator(client)
        try:
            client.create(make_cron())
            jobs = wait_for(
                lambda: client.list("kubeflow.org/v1", "JAXJob", "default"),
                message="JAXJob creation",
            )
            job = jobs[0]
            assert job["metadata"]["labels"]["kubedl.io/cron-name"] == "e2e"
            worker = job["spec"]["replicaSpecs"]["Worker"]
            assert worker["replicas"] == 1  # v5e 2x2 = single host
            sel = worker["template"]["spec"]["nodeSelector"]
            assert sel["cloud.google.com/gke-tpu-topology"] == "2x2"
            res = worker["template"]["spec"]["containers"][0]["resources"]
            assert res["limits"]["google.com/tpu"] == "4"
            # status synced over the wire
            wait_for(
                lambda: (client.get("apps.kubedl.io/v1alpha1", "Cron",
                                    "default", "e2e").get("status") or {}
                         ).get("lastScheduleTime"),
                message="lastScheduleTime patch",
            )
        finally:
            mgr.stop()

    def test_history_gc_over_the_wire(self, server, client):
        mgr = self._start_operator(client)
        try:
            client.create(make_cron(history=2))
            # Jobs have no executor here (envtest style: status is
            # simulated), so mark each arrival terminal by hand; track
            # cumulative names — the GC keeps the instantaneous LIST
            # clamped, so only history can prove >historyLimit ticks fired.
            seen = set()

            def tick():
                jobs = client.list("kubeflow.org/v1", "JAXJob", "default")
                for j in jobs:
                    seen.add(j["metadata"]["name"])
                    if not (j.get("status") or {}).get("conditions"):
                        client.patch_status(
                            "kubeflow.org/v1", "JAXJob", "default",
                            j["metadata"]["name"],
                            {"conditions": [
                                {"type": "Created", "status": "True"},
                                {"type": "Succeeded", "status": "True"},
                            ]},
                        )
                return jobs

            wait_for(lambda: tick() and len(seen) >= 4, timeout=20.0,
                     message="4+ distinct jobs fired")
            # GC must clamp live terminated workloads and history to 2.
            def gc_settled():
                jobs = tick()
                cron = client.get("apps.kubedl.io/v1alpha1", "Cron",
                                  "default", "e2e")
                hist = (cron.get("status") or {}).get("history") or []
                terminated = [
                    j for j in jobs
                    if any(c["type"] == "Succeeded"
                           for c in (j.get("status") or {})
                           .get("conditions") or [])
                ]
                return 0 < len(hist) <= 2 and len(terminated) <= 2
            wait_for(gc_settled, timeout=15.0, message="history GC to 2")
            assert len(seen) >= 4  # GC deleted at least 2 old workloads
        finally:
            mgr.stop()

    def test_replace_policy_over_the_wire(self, server, client):
        mgr = self._start_operator(client)
        try:
            client.create(make_cron(policy="Replace"))
            first = wait_for(
                lambda: client.list("kubeflow.org/v1", "JAXJob", "default"),
                message="first workload",
            )[0]["metadata"]["name"]
            # Leave it non-terminal: Replace must DELETE it on the next tick.
            def replaced():
                names = [j["metadata"]["name"] for j in
                         client.list("kubeflow.org/v1", "JAXJob", "default")]
                return names and first not in names
            wait_for(replaced, timeout=15.0,
                     message="active workload replaced")
        finally:
            mgr.stop()


class TestLeaderElectionE2E:
    """HA over the wire (VERDICT r3 #7): two managers with
    ``leader_elect=True`` against one HTTP apiserver — the deployment the
    chart defaults to (``leaderElection.enable: true``, replicas>1).
    One becomes ready, the standby does not reconcile; when the leader
    dies, the standby takes over within the lease window and the
    controller keeps working."""

    def _operator(self, server, identity):
        capi = ClusterAPIServer(
            ClusterConfig(server.url, token=TOKEN), scheme=default_scheme()
        )
        mgr = Manager(
            capi, max_concurrent_reconciles=2,
            leader_elect=True, identity=identity, lease_duration_s=2.0,
        )
        rec = CronReconciler(capi)
        mgr.add_controller("cron", rec.reconcile, for_gvk=GVK_CRON,
                           owns=default_scheme().workload_kinds())
        mgr.start()
        capi.start_watches([GVK_CRON] + default_scheme().workload_kinds())
        return capi, mgr

    def test_failover(self, server, client):
        capi1, mgr1 = self._operator(server, "op-1")
        capi2, mgr2 = self._operator(server, "op-2")
        try:
            wait_for(lambda: mgr1.readyz() or mgr2.readyz(),
                     message="a leader")
            leader, standby = (
                (mgr1, mgr2) if mgr1.readyz() else (mgr2, mgr1)
            )
            # Exactly one leader; the lease names the winner.
            assert not standby.readyz()
            lease = client.get(
                "coordination.k8s.io/v1", "Lease", "kube-system",
                "619a52b8.kubedl.io",
            )
            assert lease["spec"]["holderIdentity"] == leader.identity

            # Work flows under the current leader.
            client.create(make_cron("ha"))
            wait_for(
                lambda: client.list("kubeflow.org/v1", "JAXJob", "default"),
                message="workload under first leader",
            )

            # Leader dies (stop = crash: no more renewals).
            leader.stop()
            wait_for(lambda: standby.readyz(), timeout=15.0,
                     message="standby takeover")
            lease = client.get(
                "coordination.k8s.io/v1", "Lease", "kube-system",
                "619a52b8.kubedl.io",
            )
            assert lease["spec"]["holderIdentity"] == standby.identity

            # And the controller still works after failover: a second cron
            # must be reconciled by the new leader.
            client.create(make_cron("ha2"))
            wait_for(
                lambda: [
                    j for j in client.list(
                        "kubeflow.org/v1", "JAXJob", "default")
                    if j["metadata"]["labels"]["kubedl.io/cron-name"] == "ha2"
                ],
                timeout=15.0, message="workload under new leader",
            )
        finally:
            mgr1.stop()
            mgr2.stop()
            capi1.stop()
            capi2.stop()


class TestGetSubcommand:
    """`cron-operator-tpu get` — the kubectl-printcolumn surface for
    standalone deployments (the reference delegates inspection to kubectl
    + CRD printcolumns, cron_types.go:33-36)."""

    def test_get_crons_and_workloads(self, server, client, capsys):
        from cron_operator_tpu.cli.main import main as cli_main

        client.create(make_cron("inspect", schedule="*/5 * * * *"))
        client.patch_status(
            "apps.kubedl.io/v1alpha1", "Cron", "default", "inspect",
            {"lastScheduleTime": "2026-07-29T10:00:00Z"},
        )
        client.create({
            "apiVersion": "kubeflow.org/v1", "kind": "JAXJob",
            "metadata": {"name": "inspect-1", "namespace": "default",
                         "labels": {"kubedl.io/cron-name": "inspect"}},
            "spec": {"replicaSpecs": {"Worker": {"replicas": 1}}},
        })
        client.patch_status(
            "kubeflow.org/v1", "JAXJob", "default", "inspect-1",
            {"conditions": [{"type": "Running", "status": "True"}]},
        )

        rc = cli_main(["get", "crons", "--server", server.url,
                       "--token", TOKEN])
        out = capsys.readouterr().out
        assert rc == 0
        lines = out.strip().splitlines()
        assert lines[0].split() == [
            "NAME", "SCHEDULE", "SUSPEND", "LAST", "SCHEDULE", "AGE",
        ]
        row = [l for l in lines if l.startswith("inspect")][0]
        assert "*/5 * * * *" in row
        assert "false" in row
        assert "2026-07-29T10:00:00Z" in row

        rc = cli_main(["get", "workloads", "--server", server.url,
                       "--token", TOKEN])
        out = capsys.readouterr().out
        assert rc == 0
        row = [l for l in out.splitlines() if "inspect-1" in l][0]
        assert "JAXJob" in row and "Running" in row and "inspect" in row

    def test_get_fails_cleanly_when_server_unreachable(self, capsys):
        from cron_operator_tpu.cli.main import main as cli_main

        rc = cli_main(["get", "crons", "--server",
                       "http://127.0.0.1:1"])  # nothing listens there
        captured = capsys.readouterr()
        assert rc == 1
        assert captured.err.startswith("error: ")
        assert "Traceback" not in captured.err


class TestDescribeSubcommand:
    def test_describe_cron_shows_status_and_events(self, server, client,
                                                   capsys):
        """kubectl-describe analog: spec summary, status, and the
        object's events — including events recorded by the EMBEDDED
        control plane (persisted as corev1 Event objects)."""
        from cron_operator_tpu.cli.main import main as cli_main

        client.create(make_cron("desc", schedule="*/2 * * * *",
                                policy="Forbid", history=4))
        client.patch_status(
            "apps.kubedl.io/v1alpha1", "Cron", "default", "desc",
            {"lastScheduleTime": "2026-07-30T01:00:00Z",
             "active": [{"kind": "JAXJob", "name": "desc-1"}]},
        )
        # Embedded-side event (what the reconciler records in-process).
        server.api.record_event(
            {"apiVersion": "apps.kubedl.io/v1alpha1", "kind": "Cron",
             "metadata": {"name": "desc", "namespace": "default"}},
            "Warning", "TooManyMissedTimes", "too many missed start times",
        )

        rc = cli_main(["describe", "cron", "desc", "--server", server.url,
                       "--token", TOKEN])
        out = capsys.readouterr().out
        assert rc == 0
        assert "Schedule:           */2 * * * *" in out
        assert "Concurrency Policy: Forbid" in out
        assert "Last Schedule Time: 2026-07-30T01:00:00Z" in out
        assert "JAXJob/desc-1" in out
        assert "TooManyMissedTimes" in out

    def test_describe_missing_cron_fails_cleanly(self, server, capsys):
        from cron_operator_tpu.cli.main import main as cli_main

        rc = cli_main(["describe", "cron", "nope", "--server", server.url,
                       "--token", TOKEN])
        captured = capsys.readouterr()
        assert rc == 1
        assert "not found" in captured.err


class TestOperationalVerbs:
    """`suspend` / `resume` / `trigger` — the reference's kubectl idioms
    (`kubectl patch ... spec.suspend`, `kubectl create job --from=cronjob`)
    carried by the CLI for standalone deployments."""

    def test_suspend_and_resume_flip_spec(self, server, client, capsys):
        from cron_operator_tpu.cli.main import main as cli_main

        client.create(make_cron("pausable", schedule="*/5 * * * *"))

        rc = cli_main(["suspend", "cron", "pausable",
                       "--server", server.url, "--token", TOKEN])
        assert rc == 0
        assert "suspended" in capsys.readouterr().out
        cron = client.get("apps.kubedl.io/v1alpha1", "Cron",
                          "default", "pausable")
        assert cron["spec"]["suspend"] is True

        # idempotent: suspending a suspended cron reports unchanged
        rc = cli_main(["suspend", "cron", "pausable",
                       "--server", server.url, "--token", TOKEN])
        assert rc == 0
        assert "unchanged" in capsys.readouterr().out

        rc = cli_main(["resume", "cron", "pausable",
                       "--server", server.url, "--token", TOKEN])
        assert rc == 0
        assert "resumed" in capsys.readouterr().out
        cron = client.get("apps.kubedl.io/v1alpha1", "Cron",
                          "default", "pausable")
        assert cron["spec"]["suspend"] is False

    def test_trigger_creates_labeled_owned_workload(self, server, client,
                                                    capsys):
        from cron_operator_tpu.cli.main import main as cli_main

        client.create(make_cron("manual", schedule="0 0 1 1 *"))

        rc = cli_main(["trigger", "cron", "manual",
                       "--server", server.url, "--token", TOKEN])
        out = capsys.readouterr().out
        assert rc == 0
        assert "jaxjob/manual-manual-" in out

        jobs = client.list("kubeflow.org/v1", "JAXJob",
                           namespace="default")
        mine = [j for j in jobs
                if j["metadata"]["name"].startswith("manual-manual-")]
        assert len(mine) == 1
        meta = mine[0]["metadata"]
        # labeled + owner-ref'd like a scheduled run: status sync, history
        # and cascade GC pick it up unmodified
        assert meta["labels"]["kubedl.io/cron-name"] == "manual"
        owner = meta["ownerReferences"][0]
        assert owner["kind"] == "Cron" and owner["name"] == "manual"
        # TPU admission ran, same as the tick path: scheduling metadata is
        # on the POSTed object (make_cron's template is v5e 2x2)
        pod = (mine[0]["spec"]["replicaSpecs"]["Worker"]["template"]
               ["spec"])
        assert "gke-tpu-topology" in str(pod.get("nodeSelector", {}))
        # the manual run is visible as an event on the cron
        events = client.list("v1", "Event", "default")
        assert any(e.get("reason") == "ManualTrigger" for e in events)

    def test_verbs_fail_cleanly_on_missing_cron(self, server, capsys):
        from cron_operator_tpu.cli.main import main as cli_main

        for verb in ("suspend", "resume", "trigger"):
            rc = cli_main([verb, "cron", "ghost",
                           "--server", server.url, "--token", TOKEN])
            captured = capsys.readouterr()
            assert rc == 1
            assert "not found" in captured.err


class TestDeleteSubcommand:
    def test_delete_cascades_to_owned_workloads(self, server, client,
                                                capsys):
        from cron_operator_tpu.cli.main import main as cli_main

        client.create(make_cron("doomed", schedule="0 0 1 1 *"))
        rc = cli_main(["trigger", "cron", "doomed",
                       "--server", server.url, "--token", TOKEN])
        assert rc == 0
        capsys.readouterr()

        rc = cli_main(["delete", "cron", "doomed",
                       "--server", server.url, "--token", TOKEN])
        out = capsys.readouterr().out
        assert rc == 0 and "deleted" in out
        assert client.try_get("apps.kubedl.io/v1alpha1", "Cron",
                              "default", "doomed") is None
        # owner-ref cascade: the manually triggered workload goes too
        import time as _t
        deadline = _t.time() + 5
        while _t.time() < deadline:
            left = [
                j for j in client.list("kubeflow.org/v1", "JAXJob",
                                       namespace="default")
                if j["metadata"]["name"].startswith("doomed-manual-")
            ]
            if not left:
                break
            _t.sleep(0.1)
        assert not left

    def test_delete_missing_fails_cleanly(self, server, capsys):
        from cron_operator_tpu.cli.main import main as cli_main

        rc = cli_main(["delete", "cron", "ghost",
                       "--server", server.url, "--token", TOKEN])
        captured = capsys.readouterr()
        assert rc == 1 and "not found" in captured.err


class TestStartDebugEndpoints:
    """`start` serves the flight recorder over real sockets: /debug/audit
    (filterable, WAL-positioned records), /debug/shards (durability
    view), /debug/traces — next to /metrics, same server."""

    def _free_port(self):
        import socket

        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    def _get_json(self, port, path):
        import json
        import urllib.request

        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=5
        ) as resp:
            assert resp.headers["Content-Type"] == "application/json"
            return json.loads(resp.read().decode())

    def test_start_serves_flight_recorder(self, tmp_path):
        import json
        import threading
        import urllib.request

        from cron_operator_tpu.cli.main import main as cli_main

        manifest = tmp_path / "cron.yaml"
        manifest.write_text(json.dumps({
            "apiVersion": "apps.kubedl.io/v1alpha1", "kind": "Cron",
            "metadata": {"name": "obs", "namespace": "default"},
            "spec": {
                "schedule": "@every 1s",
                "template": {"workload": {
                    "apiVersion": "kubeflow.org/v1", "kind": "JAXJob",
                    "metadata": {"annotations": {
                        "tpu.kubedl.io/simulate-duration": "50ms",
                    }},
                    "spec": {"replicaSpecs": {"Worker": {"replicas": 1}}},
                }},
            },
        }))
        audit_log = tmp_path / "audit.jsonl"
        port = self._free_port()
        rc = []
        t = threading.Thread(
            target=lambda: rc.append(cli_main([
                "start",
                "--metrics-bind-address", f":{port}",
                "--metrics-secure=false",
                "--health-probe-bind-address", "0",
                "--data-dir", str(tmp_path / "state"),
                "--audit-log", str(audit_log),
                "--load", str(manifest),
                "--run-for", "6",
            ])),
            daemon=True,
        )
        t.start()

        def _fired():
            try:
                doc = self._get_json(
                    port, "/debug/audit?kind=decision&event=tick_fired"
                )
            except Exception:
                return None
            return doc if doc["matched"] >= 1 else None

        audit = wait_for(_fired, timeout=15.0,
                         message="tick_fired audit record over HTTP")
        fired = audit["records"][-1]
        assert fired["trace_id"]
        assert "/JAXJob/default/obs-" in fired["key"]

        # store verbs carry WAL positions the /debug/shards view matches
        store_doc = self._get_json(port, "/debug/audit?kind=store&limit=5")
        assert store_doc["matched"] >= 1
        assert all(r["wal_pos"] is not None
                   for r in store_doc["records"])

        shards = self._get_json(port, "/debug/shards")
        assert shards["n_shards"] == 1
        (entry,) = shards["shards"]
        assert entry["wal"]["records_appended"] >= 1
        assert entry["leader"]  # the embedded manager's identity

        traces = self._get_json(port, "/debug/traces")
        assert isinstance(traces["traces"], list)

        # /metrics exposes the audit counter families next door
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=5
        ) as resp:
            body = resp.read().decode()
        assert "# TYPE audit_records_total counter" in body
        assert 'audit_records_total{kind="store"}' in body

        t.join(timeout=30)
        assert not t.is_alive()
        assert rc == [0]

        # the JSONL tape persisted every audited record
        lines = [json.loads(line) for line in
                 audit_log.read_text().splitlines() if line.strip()]
        assert any(r["event"] == "tick_fired" for r in lines)
        assert any(r["kind"] == "store" for r in lines)

    def test_start_serves_timeline_and_fleet_observatory(self, tmp_path):
        """/debug/timeline (bounded metric history) and /debug/fleet
        (derived utilization/deadline accounting) through the live
        start path, with a fleet pool so the observatory has capacity
        books to sample; shutdown persists the observatory rollup and
        the throughput-matrix sidecar into --data-dir."""
        import json
        import threading

        from cron_operator_tpu.cli.main import main as cli_main

        manifest = tmp_path / "cron.yaml"
        manifest.write_text(json.dumps({
            "apiVersion": "apps.kubedl.io/v1alpha1", "kind": "Cron",
            "metadata": {"name": "obs-fleet", "namespace": "default"},
            "spec": {
                "schedule": "@every 1s",
                "template": {"workload": {
                    "apiVersion": "kubeflow.org/v1", "kind": "JAXJob",
                    "metadata": {"annotations": {
                        "tpu.kubedl.io/simulate-duration": "50ms",
                    }},
                    "spec": {"replicaSpecs": {"Worker": {"replicas": 1}}},
                }},
            },
        }))
        port = self._free_port()
        rc = []
        t = threading.Thread(
            target=lambda: rc.append(cli_main([
                "start",
                "--metrics-bind-address", f":{port}",
                "--metrics-secure=false",
                "--health-probe-bind-address", "0",
                "--data-dir", str(tmp_path / "state"),
                "--fleet-pool", "cpu=2",
                "--load", str(manifest),
                "--run-for", "6",
            ])),
            daemon=True,
        )
        t.start()

        def _history():
            try:
                doc = self._get_json(
                    port,
                    "/debug/timeline?family=cron_ticks_fired_total&res=1s",
                )
            except Exception:
                return None
            pts = doc["series"].get("cron_ticks_fired_total") or []
            return doc if pts else None

        timeline = wait_for(_history, timeout=15.0,
                            message="tick history on /debug/timeline")
        assert timeline["res"] == "1s"
        assert set(timeline["resolutions"]) >= {"1s", "10s", "60s"}
        pts = timeline["series"]["cron_ticks_fired_total"]
        # Counters mirror their cumulative total: history max is the
        # live counter value so far, and never decreases across buckets.
        assert all(p["count"] >= 1 for p in pts)
        assert pts[-1]["max"] >= 1.0

        def _fleet():
            try:
                doc = self._get_json(port, "/debug/fleet")
            except Exception:
                return None
            # Wait until the fired ticks show up in deadline accounting.
            if doc["observatory"]["deadline_slo"]["hits"] < 1:
                return None
            return doc

        fleet_doc = wait_for(_fleet, timeout=15.0,
                             message="deadline accounting on /debug/fleet")
        obs = fleet_doc["observatory"]
        assert obs["deadline_slo"]["hit_rate"] > 0
        assert "default/obs-fleet" in obs["deadline_slo"]["per_cron"]
        assert fleet_doc["pool"]["cpu"]["count"] == 2
        assert fleet_doc["fleet"]["policy"] == "hetero"
        util = fleet_doc["observatory"]["utilization"]
        assert all(
            0.0 <= row["utilization"] <= 1.0 for row in util.values()
        )

        t.join(timeout=30)
        assert not t.is_alive()
        assert rc == [0]

        # Shutdown rolled up accounting history and saved the matrix
        # sidecar for the next boot.
        rollup = tmp_path / "state" / "observatory.jsonl"
        assert rollup.exists()
        lines = [json.loads(line) for line in
                 rollup.read_text().splitlines() if line.strip()]
        assert lines and "deadline_slo" in lines[-1]
        matrix = tmp_path / "state" / "fleet_matrix.json"
        assert matrix.exists()
        assert "rates" in json.loads(matrix.read_text())


class TestServedAPITLS:
    """HTTPS on the served API (the reference webhook-server cert
    scaffolding analog, start.go:100-119): provided cert pair, bearer
    token, the production ClusterAPIServer client verifying against the
    cert — the full inbound-TLS loop over a real socket."""

    def test_https_round_trip_with_verification(self, tmp_path):
        from cron_operator_tpu.utils.tlsutil import (
            self_signed_cert,
            server_context,
        )

        cert, key = self_signed_cert(dir=str(tmp_path))
        srv = HTTPAPIServer(
            token=TOKEN,
            tls_ctx=server_context(cert, key),
        )
        srv.start()
        try:
            assert srv.url.startswith("https://")
            capi = ClusterAPIServer(
                ClusterConfig(srv.url, token=TOKEN, ca_file=cert),
                scheme=default_scheme(),
            )
            try:
                capi.create(make_cron("tls-cron", tpu=False))
                got = capi.get(
                    "apps.kubedl.io/v1alpha1", "Cron", "default", "tls-cron"
                )
                assert got["metadata"]["name"] == "tls-cron"
            finally:
                capi.stop()

            # A client that verifies against the system trust store (no
            # ca_file) must REJECT the self-signed server — TLS is doing
            # its job, not just decorating the URL.
            import urllib.error

            strict = ClusterAPIServer(
                ClusterConfig(srv.url, token=TOKEN),
                scheme=default_scheme(),
            )
            try:
                with pytest.raises((ApiError, urllib.error.URLError, OSError)):
                    strict.get(
                        "apps.kubedl.io/v1alpha1", "Cron", "default",
                        "tls-cron",
                    )
            finally:
                strict.stop()
        finally:
            srv.stop()

    def test_watch_stream_over_https(self, tmp_path):
        """The chunked long-lived watch must survive the TLS wrap + the
        60 s handler socket timeout (writes land every <=0.5 s)."""
        from cron_operator_tpu.utils.tlsutil import (
            self_signed_cert,
            server_context,
        )

        cert, key = self_signed_cert(dir=str(tmp_path))
        srv = HTTPAPIServer(token=TOKEN, tls_ctx=server_context(cert, key))
        srv.start()
        capi = None
        try:
            capi = ClusterAPIServer(
                ClusterConfig(srv.url, token=TOKEN, ca_file=cert),
                scheme=default_scheme(),
            )
            seen = []
            capi.add_watcher(
                lambda ev: seen.append(ev.object["metadata"]["name"])
            )
            capi.start_watches([GVK_CRON])
            time.sleep(0.3)
            capi.create(make_cron("tls-watched", tpu=False))
            wait_for(lambda: "tls-watched" in seen,
                     message="watch event over TLS")
        finally:
            if capi is not None:
                capi.stop()
            srv.stop()
