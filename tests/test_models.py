"""Model-zoo shape/dtype tests (CPU, tiny shapes)."""

import jax
import jax.numpy as jnp
import pytest

from cron_operator_tpu.models import MLP, Bert, BertConfig, ResNet18, ResNet50


@pytest.fixture(scope="module")
def cpu0():
    return jax.devices("cpu")[0]


def test_mlp_shapes(cpu0):
    with jax.default_device(cpu0):
        m = MLP()
        x = jnp.zeros((4, 28, 28, 1))
        params = m.init(jax.random.PRNGKey(0), x)["params"]
        out = m.apply({"params": params}, x)
    assert out.shape == (4, 10)
    assert out.dtype == jnp.float32  # logits come back f32 for a stable loss


def test_resnet18_shapes(cpu0):
    with jax.default_device(cpu0):
        m = ResNet18(num_classes=10)
        x = jnp.zeros((2, 64, 64, 3))
        params = m.init(jax.random.PRNGKey(0), x)["params"]
        out = m.apply({"params": params}, x)
    assert out.shape == (2, 10)


def test_resnet50_param_count(cpu0):
    """ResNet-50 should have ~25.5M params (sanity check the architecture)."""
    with jax.default_device(cpu0):
        m = ResNet50()
        params = m.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)))[
            "params"
        ]
        n = sum(p.size for p in jax.tree_util.tree_leaves(params))
    assert 24e6 < n < 27e6, f"unexpected ResNet-50 param count {n}"


def test_bert_tiny_shapes(cpu0):
    with jax.default_device(cpu0):
        cfg = BertConfig.tiny(max_len=64, attention_impl="xla")
        m = Bert(cfg)
        ids = jnp.zeros((2, 64), jnp.int32)
        params = m.init(jax.random.PRNGKey(0), ids)["params"]
        out = m.apply({"params": params}, ids)
    assert out.shape == (2, 64, cfg.vocab_size)
    assert out.dtype == jnp.float32


def test_bert_params_are_bf16_compute_f32_store(cpu0):
    with jax.default_device(cpu0):
        cfg = BertConfig.tiny(max_len=32, attention_impl="xla")
        m = Bert(cfg)
        params = m.init(jax.random.PRNGKey(0), jnp.zeros((1, 32), jnp.int32))[
            "params"
        ]
        leaves = jax.tree_util.tree_leaves(params)
    assert all(
        p.dtype == jnp.float32 for p in leaves
    ), "params must be stored f32 (bf16 compute)"
