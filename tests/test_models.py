"""Model-zoo shape/dtype tests (CPU, tiny shapes)."""

import jax
import jax.numpy as jnp
import pytest

from cron_operator_tpu.models import MLP, Bert, BertConfig, ResNet18, ResNet50


@pytest.fixture(scope="module")
def cpu0():
    return jax.devices("cpu")[0]


def test_mlp_shapes(cpu0):
    with jax.default_device(cpu0):
        m = MLP()
        x = jnp.zeros((4, 28, 28, 1))
        params = m.init(jax.random.PRNGKey(0), x)["params"]
        out = m.apply({"params": params}, x)
    assert out.shape == (4, 10)
    assert out.dtype == jnp.float32  # logits come back f32 for a stable loss


def test_resnet18_shapes(cpu0):
    with jax.default_device(cpu0):
        m = ResNet18(num_classes=10)
        x = jnp.zeros((2, 64, 64, 3))
        params = m.init(jax.random.PRNGKey(0), x)["params"]
        out = m.apply({"params": params}, x)
    assert out.shape == (2, 10)


def test_resnet50_param_count(cpu0):
    """ResNet-50 should have ~25.5M params (sanity check the architecture)."""
    with jax.default_device(cpu0):
        m = ResNet50()
        params = m.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)))[
            "params"
        ]
        n = sum(p.size for p in jax.tree_util.tree_leaves(params))
    assert 24e6 < n < 27e6, f"unexpected ResNet-50 param count {n}"


def test_bert_tiny_shapes(cpu0):
    with jax.default_device(cpu0):
        cfg = BertConfig.tiny(max_len=64, attention_impl="xla")
        m = Bert(cfg)
        ids = jnp.zeros((2, 64), jnp.int32)
        params = m.init(jax.random.PRNGKey(0), ids)["params"]
        out = m.apply({"params": params}, ids)
    assert out.shape == (2, 64, cfg.vocab_size)
    assert out.dtype == jnp.float32


def test_bert_params_are_bf16_compute_f32_store(cpu0):
    with jax.default_device(cpu0):
        cfg = BertConfig.tiny(max_len=32, attention_impl="xla")
        m = Bert(cfg)
        params = m.init(jax.random.PRNGKey(0), jnp.zeros((1, 32), jnp.int32))[
            "params"
        ]
        leaves = jax.tree_util.tree_leaves(params)
    assert all(
        p.dtype == jnp.float32 for p in leaves
    ), "params must be stored f32 (bf16 compute)"


class TestGPT:
    def _tiny(self, **kw):
        from cron_operator_tpu.models import GPTConfig

        return GPTConfig.tiny(max_len=32, attention_impl="xla", **kw)

    def test_shapes_and_aux(self, cpu0):
        from cron_operator_tpu.models import GPT

        with jax.default_device(cpu0):
            cfg = self._tiny()
            m = GPT(cfg)
            ids = jnp.zeros((2, 32), jnp.int32)
            params = m.init(jax.random.PRNGKey(0), ids)["params"]
            logits, aux = m.apply({"params": params}, ids)
        assert logits.shape == (2, 32, cfg.vocab_size)
        assert logits.dtype == jnp.float32
        assert aux.shape == () and float(aux) == 0.0  # dense config

    def test_causality(self, cpu0):
        """Changing token t must not affect logits at positions < t."""
        from cron_operator_tpu.models import GPT

        with jax.default_device(cpu0):
            cfg = self._tiny()
            m = GPT(cfg)
            key = jax.random.PRNGKey(1)
            ids = jax.random.randint(key, (1, 32), 0, cfg.vocab_size)
            params = m.init(jax.random.PRNGKey(0), ids)["params"]
            base, _ = m.apply({"params": params}, ids)
            mutated = ids.at[0, 20].set((ids[0, 20] + 1) % cfg.vocab_size)
            changed, _ = m.apply({"params": params}, mutated)
        import numpy as np

        np.testing.assert_allclose(
            np.asarray(base[0, :20]), np.asarray(changed[0, :20]),
            rtol=1e-4, atol=1e-4,
        )
        assert not np.allclose(
            np.asarray(base[0, 20:]), np.asarray(changed[0, 20:])
        ), "future positions should see the change"

    def test_moe_blocks_produce_aux_and_train(self, cpu0):
        from cron_operator_tpu.models import GPT
        from cron_operator_tpu.workloads.train import TrainConfig, Trainer
        from cron_operator_tpu.parallel.mesh import mesh_for_devices

        with jax.default_device(cpu0):
            cfg = self._tiny(moe_every=2, num_experts=4)
            m = GPT(cfg)
            ids = jnp.zeros((2, 32), jnp.int32)
            params = m.init(jax.random.PRNGKey(0), ids)["params"]
            assert "moe" in params["layer_1"], "layer_1 should be MoE"
            logits, aux = m.apply({"params": params}, ids)
            assert float(aux) > 0.0

            mesh = mesh_for_devices([cpu0])
            trainer = Trainer(
                lambda p, x: m.apply({"params": p}, x), params, mesh,
                TrainConfig(seq_dim_in_batch=1, labels_follow_seq=True,
                            aux_loss_in_output=True, optimizer="sgd",
                            learning_rate=0.1),
            )
            batch = {
                "x": jnp.zeros((2, 32), jnp.int32),
                "y": jnp.zeros((2, 32), jnp.int32),
            }
            s1 = trainer.step(batch)
            s2 = trainer.step(batch)
        assert jnp.isfinite(s1.loss) and jnp.isfinite(s2.loss)
        assert s2.loss < s1.loss, "two steps on one batch must reduce loss"


class TestViT:
    def _tiny(self):
        from cron_operator_tpu.models import ViT, ViTConfig

        cfg = ViTConfig.tiny()
        return ViT(cfg), cfg

    def test_shapes(self, cpu0):
        with jax.default_device(cpu0):
            model, cfg = self._tiny()
            x = jnp.zeros((2, cfg.image_size, cfg.image_size, 3))
            params = model.init(jax.random.PRNGKey(0), x)["params"]
            logits = model.apply({"params": params}, x)
            assert logits.shape == (2, cfg.num_classes)
            assert logits.dtype == jnp.float32
            # one CLS + (32/8)^2 patch positions
            assert params["pos_emb"].shape[0] == 1 + (32 // 8) ** 2

    def test_trains(self, cpu0):
        """One SGD step through the reused BERT encoder stack moves the
        loss — the encoder-sharing shim (duck-typed config) is real."""
        with jax.default_device(cpu0):
            model, cfg = self._tiny()
            x = jax.random.normal(
                jax.random.PRNGKey(1), (4, cfg.image_size, cfg.image_size, 3)
            )
            y = jnp.array([0, 1, 2, 3])
            params = model.init(jax.random.PRNGKey(0), x)["params"]

            def loss_fn(p):
                logits = model.apply({"params": p}, x)
                logp = jax.nn.log_softmax(logits)
                return -jnp.mean(
                    jnp.take_along_axis(logp, y[:, None], axis=-1)
                )

            l0, grads = jax.value_and_grad(loss_fn)(params)
            params2 = jax.tree_util.tree_map(
                lambda p, g: p - 0.05 * g, params, grads
            )
            l1 = loss_fn(params2)
            assert jnp.isfinite(l0) and l1 < l0

    def test_rejects_unaligned_image(self, cpu0):
        with jax.default_device(cpu0):
            model, cfg = self._tiny()
            with pytest.raises(ValueError, match="not divisible"):
                model.init(
                    jax.random.PRNGKey(0),
                    jnp.zeros((1, 30, 30, 3)),
                )


def test_bert_gqa_rope_trains(cpu0):
    """The shared encoder's GQA + RoPE path (also used by ViT): the fused
    qkv projection gives way to grouped q/kv projections, K/V are
    broadcast by the dispatcher, and a train step moves the loss."""
    with jax.default_device(cpu0):
        cfg = BertConfig.tiny(max_len=64, num_kv_heads=2, rope=True)
        m = Bert(cfg)
        x = jnp.zeros((2, 64), jnp.int32)
        params = m.init(jax.random.PRNGKey(0), x)["params"]
        assert "kv" in params["layer_0"] and "qkv" not in params["layer_0"]

        y = jax.random.randint(jax.random.PRNGKey(1), (2, 64), 0,
                               cfg.vocab_size)

        def loss_fn(p):
            logits = m.apply({"params": p}, y)
            logp = jax.nn.log_softmax(logits)
            return -jnp.mean(
                jnp.take_along_axis(logp, y[..., None], axis=-1)
            )

        l0, grads = jax.value_and_grad(loss_fn)(params)
        params2 = jax.tree_util.tree_map(
            lambda p, g: p - 0.1 * g, params, grads
        )
        assert jnp.isfinite(l0) and loss_fn(params2) < l0


def test_vit_gqa_rope_trains(cpu0):
    from cron_operator_tpu.models import ViT, ViTConfig

    with jax.default_device(cpu0):
        cfg = ViTConfig.tiny(num_kv_heads=2, rope=True)
        m = ViT(cfg)
        x = jax.random.normal(
            jax.random.PRNGKey(2), (2, cfg.image_size, cfg.image_size, 3)
        )
        y = jnp.array([0, 1])
        params = m.init(jax.random.PRNGKey(0), x)["params"]
        assert "pos_emb" not in params  # rope replaces the table

        def loss_fn(p):
            logp = jax.nn.log_softmax(m.apply({"params": p}, x))
            return -jnp.mean(jnp.take_along_axis(logp, y[:, None], -1))

        l0, grads = jax.value_and_grad(loss_fn)(params)
        # small step: lr 0.05 overshoots this random init uphill
        params2 = jax.tree_util.tree_map(
            lambda p, g: p - 1e-3 * g, params, grads
        )
        assert jnp.isfinite(l0) and loss_fn(params2) < l0
