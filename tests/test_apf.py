"""APF-style fair-queue admission (runtime/apf.py): seats, per-flow
round-robin dispatch, bounded queues with 429 semantics, classification."""

import threading
import time

import pytest

from cron_operator_tpu.runtime.apf import (
    DEFAULT_LEVELS,
    FairQueueAdmission,
    LevelConfig,
    TooManyRequests,
    classify,
    flow_for,
)
from cron_operator_tpu.runtime.manager import Metrics


def make_apf(seats=1, queue_depth=4, max_queued=8, timeout_s=5.0, **kw):
    return FairQueueAdmission(levels={
        "workload": LevelConfig(seats=seats, queue_depth=queue_depth,
                                max_queued=max_queued,
                                queue_timeout_s=timeout_s),
    }, **kw)


class TestSeats:
    def test_fast_path_acquire_release(self):
        apf = make_apf(seats=2)
        t1 = apf.acquire("workload", "a")
        t2 = apf.acquire("workload", "b")
        snap = apf.snapshot()["workload"]
        assert snap["in_flight"] == 2 and snap["queued"] == 0
        t1.release()
        t2.release()
        assert apf.snapshot()["workload"]["in_flight"] == 0

    def test_release_is_idempotent(self):
        apf = make_apf(seats=1)
        t = apf.acquire("workload", "a")
        t.release()
        t.release()
        assert apf.snapshot()["workload"]["in_flight"] == 0
        # the freed seat is reusable
        with apf.acquire("workload", "a"):
            assert apf.snapshot()["workload"]["in_flight"] == 1
        assert apf.snapshot()["workload"]["in_flight"] == 0

    def test_unknown_level_falls_back_to_workload(self):
        apf = make_apf(seats=1)
        t = apf.acquire("no-such-level", "a")
        assert apf.snapshot()["workload"]["in_flight"] == 1
        t.release()

    def test_levels_are_isolated(self):
        apf = FairQueueAdmission(levels={
            "system": LevelConfig(seats=1, queue_depth=1, max_queued=1,
                                  queue_timeout_s=0.05),
            "workload": LevelConfig(seats=1, queue_depth=1, max_queued=1,
                                    queue_timeout_s=0.05),
        })
        hold = apf.acquire("workload", "noisy")
        # workload exhausted; system must still admit instantly.
        t = apf.acquire("system", "controller")
        t.release()
        hold.release()

    def test_requires_workload_level(self):
        with pytest.raises(ValueError):
            FairQueueAdmission(levels={"batch": LevelConfig()})


class TestQueueing:
    def test_queue_overflow_rejects_429(self):
        apf = make_apf(seats=1, queue_depth=2, max_queued=8)
        hold = apf.acquire("workload", "x")
        filler = []

        def queue_one():
            try:
                filler.append(apf.acquire("workload", "x"))
            except TooManyRequests:
                filler.append(None)

        threads = [threading.Thread(target=queue_one) for _ in range(2)]
        for t in threads:
            t.start()
        deadline = time.monotonic() + 2.0
        while (apf.snapshot()["workload"]["queued"] < 2
               and time.monotonic() < deadline):
            time.sleep(0.005)
        assert apf.snapshot()["workload"]["queued"] == 2
        with pytest.raises(TooManyRequests) as exc:
            apf.acquire("workload", "x")
        assert exc.value.retry_after >= 1.0
        hold.release()
        for t in threads:
            t.join(timeout=2.0)

    def test_queue_wait_timeout_rejects_429(self):
        apf = make_apf(seats=1, timeout_s=0.05)
        hold = apf.acquire("workload", "x")
        t0 = time.monotonic()
        with pytest.raises(TooManyRequests):
            apf.acquire("workload", "y")
        assert time.monotonic() - t0 < 2.0
        # the abandoned waiter must not leak queue accounting
        assert apf.snapshot()["workload"]["queued"] == 0
        hold.release()
        # and the seat is still grantable afterwards
        apf.acquire("workload", "y").release()

    def test_round_robin_across_flows(self):
        """One noisy flow (3 queued) + one quiet flow (1 queued): the
        quiet request is dispatched second, not fourth."""
        apf = make_apf(seats=1)
        hold = apf.acquire("workload", "seed")
        order = []
        lock = threading.Lock()

        def worker(tag, flow):
            ticket = apf.acquire("workload", flow)
            with lock:
                order.append(tag)
            ticket.release()

        threads = []
        for tag, flow in [("n1", "noisy"), ("n2", "noisy"),
                          ("n3", "noisy"), ("q1", "quiet")]:
            th = threading.Thread(target=worker, args=(tag, flow))
            th.start()
            threads.append(th)
            # serialize enqueue order so FIFO position is deterministic
            deadline = time.monotonic() + 2.0
            want = len(threads)
            while (apf.snapshot()["workload"]["queued"] < want
                   and time.monotonic() < deadline):
                time.sleep(0.002)
        hold.release()
        for th in threads:
            th.join(timeout=5.0)
        assert order[0] == "n1"
        # round-robin: quiet's single request preempts noisy's backlog
        assert order[1] == "q1"
        assert sorted(order[2:]) == ["n2", "n3"]

    def test_free_seat_never_idles_while_requests_queue(self):
        """Regression guard: a drained-but-undeleted flow entry must not
        force new arrivals to queue behind an idle seat."""
        apf = make_apf(seats=1, timeout_s=1.0)
        # Exercise queue → grant → release so flow bookkeeping has churn.
        t = apf.acquire("workload", "a")
        res = []
        th = threading.Thread(
            target=lambda: res.append(apf.acquire("workload", "a")))
        th.start()
        deadline = time.monotonic() + 2.0
        while (apf.snapshot()["workload"]["queued"] < 1
               and time.monotonic() < deadline):
            time.sleep(0.002)
        t.release()
        th.join(timeout=2.0)
        assert res and res[0] is not None
        res[0].release()
        # Seat free again: the next acquire must not block or 429.
        t0 = time.monotonic()
        apf.acquire("workload", "b").release()
        assert time.monotonic() - t0 < 0.5


class TestTelemetry:
    def test_counters_and_gauges_emitted(self):
        m = Metrics()
        apf = make_apf(seats=1, timeout_s=0.05, metrics=m)
        t = apf.acquire("workload", "a")
        with pytest.raises(TooManyRequests):
            apf.acquire("workload", "b")
        t.release()
        assert m.get('apf_requests_total{level="workload"}') == 1
        assert m.get('apf_rejected_total{level="workload"}') == 1
        assert m.gauge('apf_inflight{level="workload"}') == 0
        hist = m.histogram('apf_queue_wait_seconds{level="workload"}')
        assert hist is not None and hist["count"] == 1


class TestClassify:
    def test_system_traffic(self):
        assert classify("PUT", name="lease-1", kind="Lease",
                        namespace="default", identity=None) == "system"
        assert classify("GET", name=None, kind="Cron",
                        namespace="kube-system", identity=None) == "system"
        assert classify("POST", name=None, kind="Cron", namespace="default",
                        identity="system:operator") == "system"

    def test_bulk_lists_are_batch(self):
        assert classify("GET", name=None, kind="Cron",
                        namespace="default", identity="alice") == "batch"

    def test_watch_and_object_verbs_are_workload(self):
        assert classify("GET", name=None, kind="Cron", namespace="default",
                        identity="alice", watch=True) == "workload"
        assert classify("GET", name="a", kind="Cron", namespace="default",
                        identity="alice") == "workload"
        assert classify("POST", name=None, kind="Cron", namespace="default",
                        identity="alice") == "workload"

    def test_flow_key_prefers_identity(self):
        assert flow_for("alice", "ns1") == "alice"
        assert flow_for(None, "ns1") == "ns1"
        assert flow_for(None, None) == "cluster-scope"

    def test_default_levels_cover_mandatory_names(self):
        assert set(DEFAULT_LEVELS) == {"system", "workload", "batch"}
