"""Live shard splitting (runtime/shard.py): ownership-map cutover,
range fencing during the dark window, router wrong-shard retries, and
crash resolution to exactly one owner per key."""

import json
import os
import shutil
import threading

import pytest

from cron_operator_tpu.runtime.kube import APIServer
from cron_operator_tpu.runtime.manager import Metrics
from cron_operator_tpu.runtime.persistence import Persistence, WrongShardError
from cron_operator_tpu.runtime.shard import (
    OWNERSHIP_FILE,
    OwnershipMap,
    RangeFilteredFollower,
    ShardedControlPlane,
    ShardRouter,
    key_hash64,
    shard_dir,
    split_pred,
)
from cron_operator_tpu.telemetry.audit import AuditJournal
from cron_operator_tpu.utils.clock import FakeClock

CRON_GVK = ("cron.tpu.example.com/v1alpha1", "TpuCronJob")

#: 1->2 split cut point: upper half of the single boot class moves.
MID = 0x8000000000000000


def _cron(name, ns="default", spec=None):
    return {
        "apiVersion": "cron.tpu.example.com/v1alpha1",
        "kind": "TpuCronJob",
        "metadata": {"namespace": ns, "name": name},
        "spec": spec or {"schedule": "* * * * *"},
    }


def _moved(ns, name):
    return key_hash64(ns, name) >= MID


def _names(n=40):
    return [f"c-{i}" for i in range(n)]


def _plane(tmp_path, **kw):
    kw.setdefault("n_shards", 1)
    kw.setdefault("clock", FakeClock())
    kw.setdefault("flush_interval_s", 0)
    return ShardedControlPlane(data_dir=str(tmp_path), **kw)


class TestLiveSplit:
    def test_split_1_to_2_end_to_end(self, tmp_path):
        m = Metrics()
        plane = _plane(tmp_path, metrics=m)
        try:
            for name in _names():
                plane.router.create(_cron(name))
            plane.router.patch_status(
                *CRON_GVK, "default", "c-0", {"phase": "Active"}
            )
            report = plane.split_shard(0)
            assert report["i6_ok"] is True
            assert (report["parent"], report["child"]) == (0, 1)
            assert report["epoch"] == 1 and report["fenced"] is True
            moved = [n for n in _names() if _moved("default", n)]
            assert report["moved"] == len(moved) > 0
            assert report["child_objects"] == len(moved)
            assert report["parent_objects"] == 40 - len(moved)
            # exactly-once: every key readable through the router, on
            # the shard the new map names, and nowhere else.
            assert len(plane.router) == 40
            for name in _names():
                owner = plane.ownership.owner("default", name)
                assert owner == (1 if _moved("default", name) else 0)
                assert plane.shards[owner].store.get_frozen(
                    *CRON_GVK, "default", name
                ) is not None
                assert plane.shards[1 - owner].store.get_frozen(
                    *CRON_GVK, "default", name
                ) is None
            # the split must not lose a status write
            keeper = plane.ownership.owner("default", "c-0")
            assert plane.shards[keeper].store.get_frozen(
                *CRON_GVK, "default", "c-0"
            )["status"] == {"phase": "Active"}
            # durable commit point on disk
            saved = OwnershipMap.load(
                os.path.join(str(tmp_path), OWNERSHIP_FILE)
            )
            assert saved is not None and saved.epoch == 1
            assert m.get('shard_splits_total{outcome="ok"}') == 1.0
            assert m.histogram("shard_split_duration_seconds")["count"] == 1
            assert m.histogram(
                "shard_split_dark_window_seconds"
            )["count"] == 1
        finally:
            plane.close()

    def test_dark_window_fences_moved_range_with_owner_hints(self, tmp_path):
        plane = _plane(tmp_path)
        probes = {}

        def hook(plan):
            pred = split_pred(plan)
            assert pred("prod", "etl-hourly")  # sanity: in moved range
            try:
                plane.shards[0].store.create(_cron("etl-hourly", ns="prod"))
                probes["refused"] = False
            except WrongShardError as err:
                probes["refused"] = True
                probes["owner"] = err.owner
                probes["epoch"] = err.map_epoch

        try:
            for name in _names(10):
                plane.router.create(_cron(name))
            plane.split_shard(0, dark_window_hook=hook)
            assert probes == {"refused": True, "owner": 1, "epoch": 1}
            # the fence stays armed after cutover: a write raced to the
            # OLD owner still refuses instead of forking the key.
            with pytest.raises(WrongShardError):
                plane.shards[0].store.create(_cron("etl-hourly", ns="prod"))
            # while the router, holding the new map, serves it fine.
            plane.router.create(_cron("etl-hourly", ns="prod"))
            assert plane.shards[1].store.get_frozen(
                *CRON_GVK, "prod", "etl-hourly"
            ) is not None
        finally:
            plane.close()

    def test_router_retries_wrong_shard_with_stale_map(self, tmp_path):
        m = Metrics()
        plane = _plane(tmp_path, metrics=m)
        try:
            for name in _names(10):
                plane.router.create(_cron(name))
            plane.split_shard(0)
            # A router still holding the epoch-0 map (a raced client):
            # its home pick hits the fenced parent, which answers with
            # the owner hint; one bounded retry lands the write.
            stale = ShardRouter(
                [s.store for s in plane.shards],
                ownership=OwnershipMap.boot(1),
                metrics=m,
            )
            stale.create(_cron("etl-hourly", ns="prod"))
            assert stale.wrong_shard_retries == 1
            assert m.get("router_wrong_shard_retries_total") == 1.0
            assert plane.shards[1].store.get_frozen(
                *CRON_GVK, "prod", "etl-hourly"
            ) is not None
        finally:
            plane.close()

    def test_router_wrong_shard_retry_exhaustion_reraises(self, tmp_path):
        """The 421-chase is BOUNDED: when every shard keeps answering
        WrongShardError past the deadline (a cutover that never lands,
        or hints that ping-pong), the router re-raises instead of
        spinning forever — the caller sees the 421, not a hang."""
        m = Metrics()

        class _AlwaysWrong:
            """A shard that refuses every write with a hint at the
            OTHER stub — the worst case: hints that chase each other."""

            def __init__(self, owner_hint):
                self.owner_hint = owner_hint
                self.calls = 0

            def create(self, obj):
                self.calls += 1
                raise WrongShardError(
                    "range moved", owner=self.owner_hint, map_epoch=9
                )

        stubs = [_AlwaysWrong(1), _AlwaysWrong(0)]
        router = ShardRouter(stubs, ownership=OwnershipMap.boot(2),
                             metrics=m)
        router.WRONG_SHARD_RETRY_DEADLINE_S = 0.2
        router.WRONG_SHARD_RETRY_SLEEP_S = 0.005
        with pytest.raises(WrongShardError) as exc:
            router.create(_cron("doomed"))
        # the hint survives exhaustion so the client can re-resolve
        assert exc.value.owner in (0, 1) and exc.value.map_epoch == 9
        assert router.wrong_shard_retries >= 2
        assert m.get("router_wrong_shard_retries_total") >= 2.0
        # both stubs were actually tried (the hint chase worked until
        # the deadline cut it off)
        assert stubs[0].calls >= 1 and stubs[1].calls >= 1

    def test_router_exhaustion_with_unaddressable_owner_hint(self):
        """Owner hint names a shard this router cannot address (child
        exists server-side, the new map not yet published here): the
        bounded retry re-resolves, sleeps, and still exhausts."""

        class _Fenced:
            def create(self, obj):
                raise WrongShardError("moved", owner=7, map_epoch=3)

        router = ShardRouter([_Fenced()], ownership=OwnershipMap.boot(1))
        router.WRONG_SHARD_RETRY_DEADLINE_S = 0.1
        router.WRONG_SHARD_RETRY_SLEEP_S = 0.005
        with pytest.raises(WrongShardError):
            router.create(_cron("doomed"))
        assert router.wrong_shard_retries >= 2

    def test_wrong_shard_exhaustion_surfaces_421_over_http(self):
        """Full wire path: shard door answers 421 → RouterServer's
        ShardClient re-raises WrongShardError → the router exhausts its
        chase → the router's OWN front door answers 421 → the outer
        client sees WrongShardError with the hints intact. No hang, no
        5xx, no breaker trip (a 421 is the shard fencing correctly)."""
        from cron_operator_tpu.runtime.apiserver_http import HTTPAPIServer
        from cron_operator_tpu.runtime.transport import (
            RouterServer,
            ShardClient,
        )

        class _FencedStore(APIServer):
            def create(self, obj):
                raise WrongShardError("range moved", owner=7, map_epoch=4)

        m = Metrics()
        store = _FencedStore(clock=FakeClock())
        door = HTTPAPIServer(api=store)
        door.start()
        try:
            rs = RouterServer(
                peers=[f"127.0.0.1:{door.port}"], metrics=m,
                start_watches=False,
            )
            rs.router.WRONG_SHARD_RETRY_DEADLINE_S = 0.2
            rs.router.WRONG_SHARD_RETRY_SLEEP_S = 0.01
            client = ShardClient(f"http://127.0.0.1:{rs.port}")
            try:
                with pytest.raises(WrongShardError) as exc:
                    client.create(_cron("doomed"))
                assert exc.value.owner == 7
                assert exc.value.map_epoch == 4
                assert m.get("router_wrong_shard_retries_total") >= 2.0
                # the shard answered promptly and correctly — its
                # breaker must still be closed
                assert rs.clients[0].breaker.state == 0
            finally:
                client.close()
                rs.close()
        finally:
            door.stop()
            store.close()

    def test_split_under_concurrent_writes_loses_nothing(self, tmp_path):
        plane = _plane(tmp_path)
        stop = threading.Event()
        acked, refused = [], []

        def storm():
            i = 0
            while not stop.is_set():
                name = f"storm-{i}"
                try:
                    plane.router.create(_cron(name))
                    acked.append(name)
                except Exception:
                    refused.append(name)
                i += 1

        t = threading.Thread(target=storm, daemon=True)
        try:
            for name in _names(20):
                plane.router.create(_cron(name))
            t.start()
            report = plane.split_shard(0)
            stop.set()
            t.join(timeout=10.0)
            assert report["i6_ok"] is True
            # every acked write readable exactly once, on its map home
            for name in acked + _names(20):
                owner = plane.ownership.owner("default", name)
                assert plane.shards[owner].store.get_frozen(
                    *CRON_GVK, "default", name
                ) is not None, name
                assert plane.shards[1 - owner].store.get_frozen(
                    *CRON_GVK, "default", name
                ) is None, name
            assert len(plane.router) == 20 + len(acked)
            # the router retried through the dark window: nothing the
            # client saw acked may be missing, and nothing was refused
            # (the storm goes through the router, which re-routes).
            assert refused == []
        finally:
            stop.set()
            plane.close()

    def test_unfenced_split_loses_acked_write_counterproof(self, tmp_path):
        plane = _plane(tmp_path)
        acked = {}

        def poison(plan):
            # Without the fence the demoted parent happily acks a write
            # on the moved range DURING the dark window...
            plane.shards[0].store.create(_cron("etl-hourly", ns="prod"))
            acked["ok"] = True

        try:
            for name in _names(10):
                plane.router.create(_cron(name))
            plane.split_shard(0, fence=False, dark_window_hook=poison)
            assert acked.get("ok") is True
            # ...and the split erases it: the child never saw it (the
            # shipper was already detached) and the parent evicted the
            # moved range. A durably-acked write is GONE — this is the
            # violation range fencing exists to prevent.
            assert plane.router.try_get(
                *CRON_GVK, "prod", "etl-hourly"
            ) is None
        finally:
            plane.close()

    def test_abort_lifts_fence_and_keeps_epoch(self, tmp_path):
        m = Metrics()
        plane = _plane(tmp_path, metrics=m)

        def boom(plan):
            raise RuntimeError("operator pulled the plug")

        try:
            for name in _names(10):
                plane.router.create(_cron(name))
            with pytest.raises(RuntimeError, match="pulled the plug"):
                plane.split_shard(0, dark_window_hook=boom)
            # clean unwind: map unchanged, parent serves the full range
            assert plane.ownership.epoch == 0 and plane.n_shards == 1
            assert plane._split_progress is None
            plane.router.create(_cron("etl-hourly", ns="prod"))
            assert len(plane.router) == 11
            assert m.get('shard_splits_total{outcome="aborted"}') == 1.0
            # and the next attempt succeeds despite the stray child dir
            report = plane.split_shard(0)
            assert report["i6_ok"] is True and plane.n_shards == 2
        finally:
            plane.close()

    def test_second_split_scales_1_to_3(self, tmp_path):
        plane = _plane(tmp_path)
        try:
            for name in _names(60):
                plane.router.create(_cron(name))
            plane.split_shard(0)
            plane.split_shard(1)
            assert plane.n_shards == 3 and plane.ownership.epoch == 2
            assert len(plane.router) == 60
            for name in _names(60):
                owner = plane.ownership.owner("default", name)
                for i, s in enumerate(plane.shards):
                    present = s.store.get_frozen(
                        *CRON_GVK, "default", name
                    ) is not None
                    assert present == (i == owner), (name, i)
        finally:
            plane.close()

    def test_owner_family_moves_as_one(self, tmp_path):
        plane = _plane(tmp_path)
        try:
            root = _cron("etl-hourly", ns="prod")  # hash in moved range
            child = _cron("etl-hourly-28916560-abc12", ns="prod")
            child["metadata"]["ownerReferences"] = [{
                "apiVersion": CRON_GVK[0], "kind": CRON_GVK[1],
                "name": "etl-hourly", "uid": "u-1", "controller": True,
            }]
            assert not _moved("prod", "etl-hourly-28916560-abc12")
            plane.router.create(root)
            plane.shards[0].store.create(child)  # co-located with owner
            plane.split_shard(0)
            # both live on the child shard: the family did not tear
            for name in ("etl-hourly", "etl-hourly-28916560-abc12"):
                assert plane.shards[1].store.get_frozen(
                    *CRON_GVK, "prod", name
                ) is not None, name
                assert plane.shards[0].store.get_frozen(
                    *CRON_GVK, "prod", name
                ) is None, name
        finally:
            plane.close()

    def test_audit_and_debug_surface_the_split(self, tmp_path):
        audit = AuditJournal()
        plane = _plane(tmp_path, audit=audit)
        try:
            for name in _names(10):
                plane.router.create(_cron(name))
            plane.split_shard(0)
            events = [r["event"] for r in audit.records(kind="cluster")]
            assert "split_started" in events
            assert "split_cutover" in events
            dbg = plane.debug_shards()
            assert dbg["ownership"]["epoch"] == 1
            assert dbg["ownership"]["n_shards"] == 2
            assert dbg["splits"] == 1 and dbg["split_in_progress"] is None
            assert {r["owner"] for r in dbg["ownership"]["ranges"]} == {0, 1}
            assert dbg["shards"][1]["ranges"] == [{
                "class": 0,
                "start": "0x8000000000000000",
                "end": "0x10000000000000000",
                "owner": 1,
            }]
            assert json.loads(plane.render_debug_json())
        finally:
            plane.close()


class TestSplitCrashResolution:
    def test_restart_after_commit_serves_every_key_once(self, tmp_path):
        plane = _plane(tmp_path)
        for name in _names(30):
            plane.router.create(_cron(name))
        plane.router.patch_status(
            *CRON_GVK, "default", "c-1", {"phase": "Active"}
        )
        plane.split_shard(0)
        plane.router.create(_cron("post-split"))
        plane.close()

        reopened = _plane(tmp_path)  # n_shards=1 arg; the map wins
        try:
            assert reopened.n_shards == 2
            assert reopened.ownership.epoch == 1
            assert len(reopened.router) == 31
            for name in _names(30) + ["post-split"]:
                owner = reopened.ownership.owner("default", name)
                assert reopened.shards[owner].store.get_frozen(
                    *CRON_GVK, "default", name
                ) is not None, name
                assert reopened.shards[1 - owner].store.get_frozen(
                    *CRON_GVK, "default", name
                ) is None, name
            keeper = reopened.ownership.owner("default", "c-1")
            assert reopened.shards[keeper].store.get_frozen(
                *CRON_GVK, "default", "c-1"
            )["status"] == {"phase": "Active"}
        finally:
            reopened.close()

    def test_crash_before_rename_leaves_parent_sole_owner(self, tmp_path):
        plane = _plane(tmp_path)
        for name in _names(20):
            plane.router.create(_cron(name))
        for s in plane.shards:
            s.persistence.flush()
        plane.close()
        # A split that died mid-materialize: the child dir exists with a
        # full copy, but the commit rename never happened.
        shutil.copytree(
            shard_dir(str(tmp_path), 0), shard_dir(str(tmp_path), 1)
        )
        reopened = _plane(tmp_path)
        try:
            assert reopened.n_shards == 1  # the map never named shard 1
            assert len(reopened.router) == 20
        finally:
            reopened.close()

    def test_crash_after_rename_keep_filter_drops_stale_copies(
        self, tmp_path
    ):
        plane = _plane(tmp_path)
        for name in _names(20):
            plane.router.create(_cron(name))
        for s in plane.shards:
            s.persistence.flush()
        plane.close()
        # A crash between the commit rename and the parent's eviction:
        # both dirs hold the moved keys, the map says the child owns
        # them. Boot must resolve to EXACTLY one owner.
        shutil.copytree(
            shard_dir(str(tmp_path), 0), shard_dir(str(tmp_path), 1)
        )
        new_map, _ = OwnershipMap.boot(1).split(0)
        new_map.save(os.path.join(str(tmp_path), OWNERSHIP_FILE))
        reopened = _plane(tmp_path)
        try:
            assert reopened.n_shards == 2
            assert len(reopened.router) == 20  # no double-applied keys
            for name in _names(20):
                owner = reopened.ownership.owner("default", name)
                assert reopened.shards[owner].store.get_frozen(
                    *CRON_GVK, "default", name
                ) is not None, name
                assert reopened.shards[1 - owner].store.get_frozen(
                    *CRON_GVK, "default", name
                ) is None, name
        finally:
            reopened.close()


class TestSingleStoreAdoption:
    """Growing an UNSHARDED data dir (root-level wal.jsonl/snapshot.json)
    into the sharded plane: `--shards 1` adopts it into shard-0 (the
    modulo-1 epoch-0 map homes every key there), N>1 refuses loudly."""

    def _seed_single_store(self, tmp_path, n=12):
        store = APIServer(clock=FakeClock())
        pers = Persistence(str(tmp_path), flush_interval_s=0)
        pers.start(store)
        for name in [f"solo-{i}" for i in range(n)]:
            store.create(_cron(name))
        pers.flush()
        pers.close()
        assert os.path.exists(os.path.join(str(tmp_path), "wal.jsonl"))

    def test_one_shard_boot_adopts_root_layout(self, tmp_path):
        self._seed_single_store(tmp_path)
        plane = _plane(tmp_path)
        try:
            assert len(plane.router) == 12
            assert not os.path.exists(
                os.path.join(str(tmp_path), "wal.jsonl"))
            # and the adopted store is splittable like any other
            plane.split_shard(0)
            for i in range(12):
                owner = plane.ownership.owner("default", f"solo-{i}")
                assert plane.shards[owner].store.get_frozen(
                    *CRON_GVK, "default", f"solo-{i}"
                ) is not None
        finally:
            plane.close()

    def test_multi_shard_boot_over_root_layout_refuses(self, tmp_path):
        self._seed_single_store(tmp_path)
        with pytest.raises(ValueError, match="single-store layout"):
            _plane(tmp_path, n_shards=2)

    def test_sharded_layout_wins_over_stale_root_files(self, tmp_path):
        plane = _plane(tmp_path)
        plane.router.create(_cron("real"))
        plane.close()
        # a stray pre-migration leftover must not clobber shard-0
        with open(os.path.join(str(tmp_path), "wal.jsonl"), "w") as f:
            f.write("")
        reopened = _plane(tmp_path)
        try:
            assert reopened.router.try_get(
                *CRON_GVK, "default", "real") is not None
        finally:
            reopened.close()


class TestRangeFilteredFollower:
    def test_ships_only_moved_range(self, tmp_path):
        _, plan = OwnershipMap.boot(1).split(0)
        pred = split_pred(plan)
        api = APIServer(FakeClock())
        pers = Persistence(str(tmp_path), flush_interval_s=0)
        pers.start(api)
        follower = RangeFilteredFollower(pred, FakeClock())
        pers.attach_follower(follower)
        names = _names(30)
        for name in names:
            api.create(_cron(name))
        api.delete(*CRON_GVK, "default", names[0])
        pers.flush()
        moved = [n for n in names[1:] if _moved("default", n)]
        assert len(follower.store) == len(moved)
        for name in moved:
            assert follower.store.get_frozen(
                *CRON_GVK, "default", name
            ) is not None
        assert follower.records_filtered > 0
        assert follower.lag_bytes == 0
        pers.close()
        api.close()
        follower.store.close()

    def test_bootstrap_filters_recovered_state(self, tmp_path):
        api = APIServer(FakeClock())
        pers = Persistence(str(tmp_path), flush_interval_s=0)
        pers.start(api)
        for name in _names(30):
            api.create(_cron(name))
        pers.flush()
        pers.close()
        api.close()
        _, plan = OwnershipMap.boot(1).split(0)
        follower = RangeFilteredFollower(split_pred(plan), FakeClock())
        follower.bootstrap(Persistence(str(tmp_path)).recover())
        moved = [n for n in _names(30) if _moved("default", n)]
        assert len(follower.store) == len(moved)
        follower.store.close()


class TestOwnershipRouting:
    def test_locate_consults_map_before_probing(self, tmp_path):
        m = Metrics()
        plane = _plane(tmp_path, metrics=m)
        try:
            for name in _names(20):
                plane.router.create(_cron(name))
            plane.split_shard(0)
            before = plane.router.probe_fallbacks
            for name in _names(20):
                assert plane.router.get(*CRON_GVK, "default", name)
            # map-directed lookups never probe
            assert plane.router.probe_fallbacks == before
            # an off-home co-located child still found, via fallback
            # ("probe-1" hashes below the cut, so its map home is the
            # parent; planting it on the unfenced child makes it
            # findable only by probing)
            assert plane.ownership.owner("default", "probe-1") == 0
            plane.shards[1].store.create(_cron("probe-1"))
            assert plane.router.get(*CRON_GVK, "default", "probe-1")
            assert plane.router.probe_fallbacks == before + 1
            assert m.get("router_probe_fallbacks_total") == 1.0
        finally:
            plane.close()
