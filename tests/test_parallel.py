"""Mesh/sharding-rule and ring-attention tests.

All meshes are built over the 8 virtual CPU devices (conftest forces
``--xla_force_host_platform_device_count=8``) — the same strategy the
driver's multichip dryrun uses, and the analog of the reference testing
multi-node behavior against envtest without a cluster (SURVEY.md §4).
"""

import jax
import jax.numpy as jnp
import pytest
from jax.sharding import PartitionSpec as P

from cron_operator_tpu.parallel.mesh import (
    batch_pspec,
    mesh_for_devices,
    plan_for_devices,
    pspec_for_shape,
    sharding_for_tree,
)
from cron_operator_tpu.parallel.ring import (
    _single_device_attention,
    ring_attention,
)


@pytest.fixture(scope="module")
def cpus():
    return jax.devices("cpu")


class TestMeshPlan:
    def test_default_all_data(self):
        plan = plan_for_devices(8)
        assert plan.axis_sizes == {"data": 8}

    def test_factored(self):
        plan = plan_for_devices(16, tensor=2, fsdp=2)
        assert plan.axis_sizes == {"data": 4, "fsdp": 2, "tensor": 2}
        assert plan.n_devices == 16

    def test_indivisible_raises(self):
        with pytest.raises(ValueError, match="not divisible"):
            plan_for_devices(8, tensor=3)

    def test_mesh_axis_names(self, cpus):
        mesh = mesh_for_devices(cpus, seq=2, tensor=2)
        assert mesh.shape == {"data": 2, "seq": 2, "tensor": 2}

    def test_wrong_device_count(self, cpus):
        with pytest.raises(ValueError, match="not divisible"):
            mesh_for_devices(cpus[:5], tensor=2)


class TestShardingRules:
    def test_bias_replicated(self, cpus):
        mesh = mesh_for_devices(cpus, fsdp=2, tensor=2)
        assert pspec_for_shape((128,), mesh) == P(None)
        assert pspec_for_shape((), mesh) == P()

    def test_matrix_tensor_then_fsdp(self, cpus):
        mesh = mesh_for_devices(cpus, fsdp=2, tensor=2)
        # last dim on tensor, largest remaining divisible dim on fsdp
        assert pspec_for_shape((512, 256), mesh) == P("fsdp", "tensor")

    def test_indivisible_dims_left_alone(self, cpus):
        mesh = mesh_for_devices(cpus, fsdp=2, tensor=2)
        assert pspec_for_shape((7, 3), mesh) == P(None, None)

    def test_data_only_mesh_replicates_params(self, cpus):
        mesh = mesh_for_devices(cpus)
        assert pspec_for_shape((512, 256), mesh) == P(None, None)

    def test_batch_pspec(self, cpus):
        mesh = mesh_for_devices(cpus, fsdp=2)
        assert batch_pspec(mesh) == P(("data", "fsdp"))
        assert batch_pspec(mesh, seq_dim=1) == P(("data", "fsdp"), None)
        mesh_seq = mesh_for_devices(cpus, seq=4)
        assert batch_pspec(mesh_seq, seq_dim=1) == P(("data",), "seq")

    def test_sharding_for_tree(self, cpus):
        mesh = mesh_for_devices(cpus, fsdp=2)
        tree = {"w": jnp.zeros((16, 4)), "b": jnp.zeros((4,))}
        sh = sharding_for_tree(tree, mesh)
        assert sh["w"].spec == P("fsdp", None)
        assert sh["b"].spec == P(None)


class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference(self, cpus, causal):
        mesh = mesh_for_devices(cpus, seq=4)
        key = jax.random.PRNGKey(0)
        b, s, h, d = 4, 64, 2, 16
        with jax.default_device(cpus[0]):
            q, k, v = (
                jax.random.normal(kk, (b, s, h, d), jnp.float32)
                for kk in jax.random.split(key, 3)
            )
            ref = _single_device_attention(q, k, v, causal=causal)
            out = jax.jit(
                lambda q, k, v: ring_attention(q, k, v, mesh, causal=causal)
            )(q, k, v)
        assert jnp.max(jnp.abs(out - ref)) < 1e-5

    def test_full_ring_no_data_axis(self, cpus):
        mesh = mesh_for_devices(cpus, seq=8)
        key = jax.random.PRNGKey(1)
        with jax.default_device(cpus[0]):
            q, k, v = (
                jax.random.normal(kk, (2, 128, 2, 8), jnp.float32)
                for kk in jax.random.split(key, 3)
            )
            ref = _single_device_attention(q, k, v, causal=True)
            out = ring_attention(q, k, v, mesh, causal=True)
        assert jnp.max(jnp.abs(out - ref)) < 1e-5

    def test_degenerate_mesh_falls_back(self, cpus):
        mesh = mesh_for_devices(cpus)  # no seq axis
        with jax.default_device(cpus[0]):
            q = jnp.ones((2, 16, 2, 8))
            out = ring_attention(q, q, q, mesh)
        assert out.shape == (2, 16, 2, 8)

    def test_indivisible_seq_raises_for_real_batch(self, cpus):
        """A real batch whose sequence doesn't divide the ring must fail
        loudly instead of silently materializing S×S attention (ADVICE r1)."""
        mesh = mesh_for_devices(cpus, seq=8)
        with jax.default_device(cpus[0]):
            q = jnp.ones((2, 100, 2, 8))  # 100 % 8 != 0, batch > 1
            with pytest.raises(ValueError, match="does not divide"):
                ring_attention(q, q, q, mesh)
            # batch-of-1 init trace keeps the documented silent fallback
            q1 = jnp.ones((1, 100, 2, 8))
            assert ring_attention(q1, q1, q1, mesh).shape == (1, 100, 2, 8)

    def test_grad_flows_through_ring(self, cpus):
        """Ring attention must be differentiable (it sits in the train step)."""
        mesh = mesh_for_devices(cpus, seq=2)
        with jax.default_device(cpus[0]):
            q = jax.random.normal(jax.random.PRNGKey(2), (2, 32, 2, 8))

            def loss(q):
                return jnp.sum(ring_attention(q, q, q, mesh) ** 2)

            g = jax.jit(jax.grad(loss))(q)
        assert g.shape == q.shape
        assert bool(jnp.all(jnp.isfinite(g)))


class TestHybridMultiSliceMesh:
    """hybrid_mesh_for_slices: DCN×ICI multislice recipe — data axis
    slice-major outermost, model axes confined within a slice."""

    def test_model_axes_stay_within_a_slice(self):
        from cron_operator_tpu.parallel.mesh import (
            group_devices_by_slice,
            hybrid_mesh_for_slices,
        )

        devs = jax.devices()  # 8 virtual CPU devices (conftest)
        mesh = hybrid_mesh_for_slices(2, tensor=2, devices=devs)
        assert dict(zip(mesh.axis_names, mesh.devices.shape)) == {
            "data": 4, "tensor": 2,
        }
        groups = group_devices_by_slice(devs, 2)
        slice_of = {id(d): i for i, g in enumerate(groups) for d in g}
        arr = mesh.devices
        # Every tensor-axis pair lives inside one slice (ICI)...
        for i in range(arr.shape[0]):
            row_slices = {slice_of[id(d)] for d in arr[i]}
            assert len(row_slices) == 1, "tensor pair crosses DCN"
        # ...and the data axis crosses slices (slice-major: first half
        # slice 0, second half slice 1).
        data_slices = [slice_of[id(arr[i, 0])] for i in range(arr.shape[0])]
        assert data_slices == [0, 0, 1, 1]

    def test_train_step_over_hybrid_mesh(self):
        import jax.numpy as jnp

        from cron_operator_tpu.models import MLP
        from cron_operator_tpu.parallel.mesh import hybrid_mesh_for_slices
        from cron_operator_tpu.workloads import data as datasets
        from cron_operator_tpu.workloads.train import TrainConfig, Trainer

        mesh = hybrid_mesh_for_slices(2, tensor=2)
        model = MLP()
        params = model.init(
            jax.random.PRNGKey(0), jnp.zeros((1, 28, 28, 1))
        )["params"]
        tr = Trainer(
            lambda p, x: model.apply({"params": p}, x), params, mesh,
            TrainConfig(optimizer="sgd", learning_rate=0.05),
        )
        it = datasets.mnist_batches(32, seed=7)
        s1, s2 = tr.step(next(it)), tr.step(next(it))
        assert jnp.isfinite(s1.loss) and jnp.isfinite(s2.loss)

    def test_uneven_slices_rejected(self):
        from cron_operator_tpu.parallel.mesh import hybrid_mesh_for_slices

        with pytest.raises(ValueError, match="not divisible"):
            hybrid_mesh_for_slices(3)  # 8 devices / 3 slices


class TestUlyssesAttention:
    """All-to-all sequence parallelism: exact parity with full attention,
    gradients, constraint enforcement, model integration."""

    def _qkv(self, b=2, s=64, h=4, d=16, key=0):
        ks = jax.random.split(jax.random.PRNGKey(key), 3)
        return tuple(
            jax.random.normal(k, (b, s, h, d), jnp.float32) for k in ks
        )

    def test_matches_reference(self):
        from cron_operator_tpu.ops.attention import reference_attention
        from cron_operator_tpu.parallel.ulysses import ulysses_attention

        mesh = mesh_for_devices(seq=4)  # seq=4 × data=2
        q, k, v = self._qkv()
        for causal in (False, True):
            out = jax.jit(
                lambda q, k, v, c=causal: ulysses_attention(
                    q, k, v, mesh, causal=c)
            )(q, k, v)
            ref = reference_attention(q, k, v, causal=causal)
            import numpy as np

            np.testing.assert_allclose(
                np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4,
            )

    def test_grads_match_reference(self):
        from cron_operator_tpu.ops.attention import reference_attention
        from cron_operator_tpu.parallel.ulysses import ulysses_attention

        mesh = mesh_for_devices(seq=4)
        q, k, v = self._qkv(key=1)

        def loss_u(q, k, v):
            return jnp.sum(
                ulysses_attention(q, k, v, mesh, causal=True) ** 2
            )

        def loss_r(q, k, v):
            return jnp.sum(reference_attention(q, k, v, causal=True) ** 2)

        gu = jax.jit(jax.grad(loss_u, argnums=(0, 1, 2)))(q, k, v)
        gr = jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v)
        import numpy as np

        for a, b in zip(gu, gr):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=3e-4, atol=3e-4,
            )

    def test_head_divisibility_enforced(self):
        from cron_operator_tpu.parallel.ulysses import ulysses_attention

        mesh = mesh_for_devices(seq=4)
        q, k, v = self._qkv(h=6)  # 6 heads over a 4-way axis
        with pytest.raises(ValueError, match="heads"):
            ulysses_attention(q, k, v, mesh)

    def test_bert_trains_with_ulysses(self):
        from cron_operator_tpu.models import Bert, BertConfig
        from cron_operator_tpu.workloads import data as datasets
        from cron_operator_tpu.workloads.train import TrainConfig, Trainer

        mesh = mesh_for_devices(seq=2)
        cfg = BertConfig.tiny(max_len=64, attention_impl="ulysses")
        m = Bert(cfg, mesh=mesh)
        params = m.init(
            jax.random.PRNGKey(0), jnp.zeros((1, 64), jnp.int32)
        )["params"]
        tr = Trainer(
            lambda p, x: m.apply({"params": p}, x), params, mesh,
            TrainConfig(seq_dim_in_batch=1, labels_follow_seq=True),
        )
        it = datasets.token_batches(4, 64, cfg.vocab_size)
        s1, s2 = tr.step(next(it)), tr.step(next(it))
        assert jnp.isfinite(s1.loss) and jnp.isfinite(s2.loss)


class TestOverlapPrimitives:
    """parallel.overlap: the chunk scheduler and stacked-chunk sharding
    the scan-chained executor builds on (PR 12)."""

    def test_chunk_schedule_plain_and_tail(self):
        from cron_operator_tpu.parallel.overlap import chunk_schedule

        assert chunk_schedule(0, 7, 3) == [3, 3, 1]  # non-divisible tail
        assert chunk_schedule(0, 6, 3) == [3, 3]
        assert chunk_schedule(4, 6, 8) == [2]  # resumed run, short rest
        assert chunk_schedule(6, 6, 4) == []  # target already reached
        assert chunk_schedule(0, 4, 1) == [1, 1, 1, 1]

    def test_chunk_schedule_boundary_snapping(self):
        """No chunk may straddle a save_every multiple: saves must land
        on their exact step, so the schedule realigns at boundaries —
        including a mid-interval start (checkpoint-restored run)."""
        from cron_operator_tpu.parallel.overlap import chunk_schedule

        assert chunk_schedule(0, 7, 5, boundary=3) == [3, 3, 1]
        assert chunk_schedule(2, 10, 4, boundary=4) == [2, 4, 2]
        for start, target, spc, bd in [
            (0, 23, 8, 5), (3, 17, 4, 4), (1, 9, 8, 3),
        ]:
            sched = chunk_schedule(start, target, spc, boundary=bd)
            assert sum(sched) == target - start
            done = start
            for c in sched:
                assert 1 <= c <= spc
                # crossing a boundary mid-chunk is the bug snapping
                # exists to prevent
                assert (done % bd) + c <= bd
                done += c

    def test_stacked_shardings_prepend_replicated_axis(self, cpus):
        from jax.sharding import NamedSharding

        from cron_operator_tpu.parallel.overlap import stacked_shardings

        mesh = mesh_for_devices(cpus)
        spec = batch_pspec(mesh)
        sh = {"x": NamedSharding(mesh, spec)}
        st = stacked_shardings(sh)
        # scan axis replicated, per-step layout untouched
        assert st["x"].spec == P(None, *spec)
        assert st["x"].mesh == mesh

    def test_grouped_yields_schedule_and_partial_tail(self):
        from cron_operator_tpu.workloads.data import grouped

        src = ({"i": n} for n in range(100))
        got = [[b["i"] for b in g] for g in grouped(src, [3, 3, 1])]
        assert got == [[0, 1, 2], [3, 4, 5], [6]]

        short = ({"i": n} for n in range(4))
        got = [[b["i"] for b in g] for g in grouped(short, [3, 3])]
        assert got == [[0, 1, 2], [3]]  # partial final group, no raise
