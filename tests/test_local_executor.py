"""Local training runtime tests: condition lifecycle, pod-group gang
modeling, entrypoint execution, cancellation, preemption recovery.

These run against a real-time clock (the executor uses threads); workloads
are millisecond-scale so the suite stays fast.
"""

import threading
import time

import pytest

from cron_operator_tpu.backends.local import LocalExecutor
from cron_operator_tpu.backends.registry import register_entrypoint
from cron_operator_tpu.runtime.kube import APIServer

JAX_AV, JAX_KIND = "kubeflow.org/v1", "JAXJob"


@pytest.fixture
def rt_api():
    return APIServer()  # real clock


@pytest.fixture
def executor(rt_api):
    ex = LocalExecutor(rt_api)
    ex.start()
    yield ex
    ex.stop()


def jax_job(name, annotations=None, replicas=1):
    return {
        "apiVersion": JAX_AV,
        "kind": JAX_KIND,
        "metadata": {
            "name": name,
            "namespace": "default",
            "annotations": annotations or {},
        },
        "spec": {"replicaSpecs": {"Worker": {"replicas": replicas}}},
    }


def wait_for(fn, timeout=5.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        result = fn()
        if result:
            return result
        time.sleep(interval)
    raise AssertionError("condition not met in time")


def conditions_of(api, name):
    obj = api.try_get(JAX_AV, JAX_KIND, "default", name)
    if obj is None:
        return []
    return [c["type"] for c in (obj.get("status") or {}).get("conditions") or []]


class TestLifecycle:
    def test_condition_lifecycle(self, rt_api, executor):
        rt_api.create(jax_job("j1"))
        wait_for(lambda: "Succeeded" in conditions_of(rt_api, "j1"))
        conds = conditions_of(rt_api, "j1")
        assert conds[:2] == ["Created", "Running"]
        assert conds[-1] == "Succeeded"
        status = rt_api.get(JAX_AV, JAX_KIND, "default", "j1")["status"]
        assert status["startTime"] and status["completionTime"]

    def test_entrypoint_runs_with_params(self, rt_api, executor):
        ran = {}

        @register_entrypoint("test-entry")
        def entry(ctx):
            ran["params"] = ctx.params
            ran["name"] = ctx.name

        rt_api.create(
            jax_job(
                "j2",
                annotations={
                    "tpu.kubedl.io/entrypoint": "test-entry",
                    "tpu.kubedl.io/param.steps": "5",
                },
            )
        )
        wait_for(lambda: "Succeeded" in conditions_of(rt_api, "j2"))
        assert ran["params"] == {"steps": "5"}
        assert ran["name"] == "j2"

    def test_failing_entrypoint_marks_failed(self, rt_api, executor):
        @register_entrypoint("test-boom")
        def boom(ctx):
            raise RuntimeError("kaboom")

        rt_api.create(
            jax_job("j3", annotations={"tpu.kubedl.io/entrypoint": "test-boom"})
        )
        wait_for(lambda: "Failed" in conditions_of(rt_api, "j3"))
        obj = rt_api.get(JAX_AV, JAX_KIND, "default", "j3")
        failed = [c for c in obj["status"]["conditions"] if c["type"] == "Failed"]
        assert "kaboom" in failed[0]["message"]

    def test_unknown_entrypoint_fails(self, rt_api, executor):
        rt_api.create(
            jax_job("j4", annotations={"tpu.kubedl.io/entrypoint": "no-such"})
        )
        wait_for(lambda: "Failed" in conditions_of(rt_api, "j4"))


class TestPodGroup:
    def test_pods_per_host_gang(self, rt_api, executor):
        rt_api.create(
            jax_job(
                "gang",
                annotations={
                    "tpu.kubedl.io/accelerator": "v5e",
                    "tpu.kubedl.io/topology": "4x4",
                    "tpu.kubedl.io/simulate-duration": "300ms",
                },
            )
        )
        pods = wait_for(
            lambda: (
                p := rt_api.list(
                    "v1", "Pod", namespace="default",
                    label_selector={"tpu.kubedl.io/job-name": "gang"},
                )
            )
            and len(p) == 4
            and p
        )
        indices = sorted(
            p["metadata"]["labels"]["tpu.kubedl.io/worker-index"]
            for p in pods)
        assert indices == ["0", "1", "2", "3"]
        # all owned by the job → deleting the job cascades the pod group
        wait_for(lambda: "Succeeded" in conditions_of(rt_api, "gang"))
        rt_api.delete(JAX_AV, JAX_KIND, "default", "gang")
        assert rt_api.list(
            "v1", "Pod", namespace="default",
            label_selector={"tpu.kubedl.io/job-name": "gang"},
        ) == []

    def test_job_deletion_cancels_run(self, rt_api, executor):
        started = threading.Event()
        stopped = threading.Event()

        @register_entrypoint("test-long")
        def long_run(ctx):
            started.set()
            ctx.cancel.wait(10)
            if ctx.should_stop():
                stopped.set()

        rt_api.create(
            jax_job("doomed", annotations={"tpu.kubedl.io/entrypoint": "test-long"})
        )
        assert started.wait(5)
        rt_api.delete(JAX_AV, JAX_KIND, "default", "doomed")
        assert stopped.wait(5)


class TestPreemption:
    def test_preemption_fails_job(self, rt_api, executor):
        rt_api.create(
            jax_job(
                "victim",
                annotations={
                    "tpu.kubedl.io/accelerator": "v5e",
                    "tpu.kubedl.io/topology": "4x4",
                    "tpu.kubedl.io/simulate-duration": "10s",
                },
            )
        )
        wait_for(lambda: "Running" in conditions_of(rt_api, "victim"))
        executor.preempt("default", "victim")
        wait_for(lambda: "Failed" in conditions_of(rt_api, "victim"))
        # slice-atomic: every host pod gone
        assert rt_api.list(
            "v1", "Pod", namespace="default",
            label_selector={"tpu.kubedl.io/job-name": "victim"},
        ) == []
        # terminal for the cron status contract
        from cron_operator_tpu.controller.workload import is_workload_finished

        _, finished = is_workload_finished(
            rt_api.get(JAX_AV, JAX_KIND, "default", "victim")
        )
        assert finished

    def test_preemption_with_restart_reruns(self, rt_api, executor):
        runs = []

        @register_entrypoint("test-restarty")
        def restarty(ctx):
            runs.append(time.monotonic())
            ctx.cancel.wait(0.2)

        rt_api.create(
            jax_job(
                "phoenix",
                annotations={
                    "tpu.kubedl.io/entrypoint": "test-restarty",
                    "tpu.kubedl.io/restart-on-preemption": "true",
                },
            )
        )
        wait_for(lambda: len(runs) >= 1)
        executor.preempt("default", "phoenix")
        wait_for(lambda: len(runs) >= 2)
        wait_for(lambda: "Succeeded" in conditions_of(rt_api, "phoenix"))
        conds = conditions_of(rt_api, "phoenix")
        assert "Restarting" in conds


class TestSubprocessIsolation:
    """Subprocess execution mode: progress streams back as JSON lines, and
    a wedged/slow child is killable without touching the operator process
    (the round-1 bench postmortem's fix)."""

    def test_mnist_runs_and_streams_progress(self, rt_api):
        ex = LocalExecutor(rt_api, isolation="subprocess")
        ex.start()
        try:
            rt_api.create(jax_job("sub-mnist", annotations={
                "tpu.kubedl.io/entrypoint": "mnist",
                "tpu.kubedl.io/param.steps": "3",
                "tpu.kubedl.io/param.batch_size": "8",
                "tpu.kubedl.io/param.platform": "cpu",
            }))
            wait_for(
                lambda: "Succeeded" in conditions_of(rt_api, "sub-mnist"),
                timeout=120.0, interval=0.2,
            )
            prog = rt_api.get(JAX_AV, JAX_KIND, "default", "sub-mnist")[
                "status"]["trainingProgress"]
            assert prog["steps_done"] == 3
            assert prog["first_step_at"] > 0
        finally:
            ex.stop()

    def test_timeout_kills_child_and_fails_job(self, rt_api):
        ex = LocalExecutor(rt_api, isolation="subprocess")
        ex.start()
        try:
            rt_api.create(jax_job("sub-slow", annotations={
                "tpu.kubedl.io/entrypoint": "mnist",
                "tpu.kubedl.io/param.steps": "100000",
                "tpu.kubedl.io/param.batch_size": "8",
                "tpu.kubedl.io/param.platform": "cpu",
                "tpu.kubedl.io/job-timeout": "3s",
            }))
            wait_for(
                lambda: "Failed" in conditions_of(rt_api, "sub-slow"),
                timeout=120.0, interval=0.2,
            )
            status = rt_api.get(JAX_AV, JAX_KIND, "default", "sub-slow")[
                "status"]
            failed = [c for c in status["conditions"]
                      if c["type"] == "Failed"][0]
            assert "budget" in failed["message"]
        finally:
            ex.stop()
