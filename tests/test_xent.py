"""Chunked cross-entropy (ops.xent): exact parity with the naive
full-logits computation — loss AND both gradients — across chunk sizes,
including vocab sizes that do not divide the chunk."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cron_operator_tpu.ops.xent import chunked_cross_entropy

T, D, V = 24, 16, 100


def _naive(hidden, table, labels):
    logits = hidden.reshape(-1, hidden.shape[-1]).astype(jnp.float32) @ (
        table.astype(jnp.float32).T
    )
    y = labels.reshape(-1)
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    picked = jnp.take_along_axis(logits, y[:, None], axis=-1)[:, 0]
    return jnp.mean(lse - picked)


@pytest.fixture(scope="module")
def data():
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(0), 3)
    hidden = jax.random.normal(k1, (2, T // 2, D))  # leading dims [b, s]
    table = jax.random.normal(k2, (V, D)) * 0.1
    labels = jax.random.randint(k3, (2, T // 2), 0, V)
    return hidden, table, labels


class TestForward:
    @pytest.mark.parametrize("chunk", [V, 32, 33, 7])
    def test_matches_naive(self, data, chunk):
        hidden, table, labels = data
        got = chunked_cross_entropy(hidden, table, labels, chunk)
        want = _naive(hidden, table, labels)
        np.testing.assert_allclose(float(got), float(want), rtol=1e-5)

    def test_bf16_hidden(self, data):
        hidden, table, labels = data
        got = chunked_cross_entropy(
            hidden.astype(jnp.bfloat16), table, labels, 32
        )
        want = _naive(hidden.astype(jnp.bfloat16), table, labels)
        np.testing.assert_allclose(float(got), float(want), rtol=1e-2)


class TestBackward:
    @pytest.mark.parametrize("chunk", [V, 32, 7])
    def test_grads_match_naive(self, data, chunk):
        hidden, table, labels = data

        g_chunked = jax.grad(
            lambda h, w: chunked_cross_entropy(h, w, labels, chunk),
            argnums=(0, 1),
        )(hidden, table)
        g_naive = jax.grad(
            lambda h, w: _naive(h, w, labels), argnums=(0, 1)
        )(hidden, table)
        for a, b in zip(g_chunked, g_naive):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6,
            )

    def test_jits_and_composes_with_optimizer_step(self, data):
        hidden, table, labels = data

        @jax.jit
        def step(h, w):
            loss, (dh, dw) = jax.value_and_grad(
                lambda h, w: chunked_cross_entropy(h, w, labels, 32),
                argnums=(0, 1),
            )(h, w)
            return loss, h - 0.1 * dh, w - 0.1 * dw

        l1, hidden2, table2 = step(hidden, table)
        l2, _, _ = step(hidden2, table2)
        assert float(l2) < float(l1), "one step on fixed data must descend"


class TestGPTIntegration:
    def test_fused_loss_matches_standard_path(self):
        """The gpt entrypoint's fused_xent mode must produce the SAME
        first-step loss as the standard logits path (same init/data
        seeds) — fusion changes memory, not math."""
        from cron_operator_tpu.backends.registry import (
            JobContext,
            resolve_entrypoint,
        )

        def run(fused):
            ctx = JobContext(
                name="x", namespace="default", job={},
                params={
                    "steps": "1", "batch_size": "8", "seq_len": "32",
                    "size": "tiny", "attention": "xla", "platform": "cpu",
                    "fused_xent": "1" if fused else "0",
                },
            )
            resolve_entrypoint("gpt")(ctx)
            return ctx.progress["last_loss"]

        l_std = run(False)
        l_fused = run(True)
        assert abs(l_std - l_fused) < 5e-3, (l_std, l_fused)
