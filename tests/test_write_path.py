"""Write-path hot loop: structural-sharing commits, no-op status
elision, generation semantics, coalesced watch fan-out, and the
zero-write steady-state guarantee under a live Manager.

Companion to tests/test_kube_store.py (which pins the store's base
semantics — rv monotonicity, conflict detection, snapshot isolation);
this file pins the *performance contracts* the fire-storm bench
(hack/controlplane_bench.py) relies on.
"""

from __future__ import annotations

import threading
from datetime import timedelta

from cron_operator_tpu.api.scheme import GVK_CRON, default_scheme
from cron_operator_tpu.controller import CronReconciler
from cron_operator_tpu.runtime import APIServer, Manager
from cron_operator_tpu.runtime.frozen import (
    FrozenDict,
    FrozenList,
    freeze,
    freeze_delta,
)
from cron_operator_tpu.utils.clock import FakeClock

CRON_API = "apps.kubedl.io/v1alpha1"
WL_API = "kubeflow.org/v1"
WL_KIND = "JAXJob"
LABEL_CRON_NAME = "kubedl.io/cron-name"

COMMIT_VERBS = ("create", "update", "patch_status", "delete")


def _commits(metrics) -> float:
    return sum(
        metrics.get(f'apiserver_commits_total{{verb="{v}"}}') or 0.0
        for v in COMMIT_VERBS
    )


def _cron(name: str, schedule: str = "0 * * * *") -> dict:
    return {
        "apiVersion": CRON_API,
        "kind": "Cron",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {
            "schedule": schedule,
            "concurrencyPolicy": "Allow",
            "template": {"workload": {
                "apiVersion": WL_API,
                "kind": WL_KIND,
                "metadata": {},
                "spec": {"replicaSpecs": {"Worker": {"replicas": 1}}},
            }},
        },
    }


class TestFreezeDelta:
    """Structural sharing: unchanged subtrees are the PREVIOUS frozen
    objects by identity, making ``is`` a free nothing-changed test."""

    def test_identical_tree_returns_prev_by_identity(self):
        prev = freeze({"spec": {"a": [1, 2]}, "status": {"x": "y"}})
        assert freeze_delta({"spec": {"a": [1, 2]}, "status": {"x": "y"}},
                            prev) is prev

    def test_status_only_change_shares_spec_subtree(self):
        prev = freeze({"spec": {"deep": {"tree": [1, 2, 3]}},
                       "status": {"n": 1}})
        new = freeze_delta({"spec": {"deep": {"tree": [1, 2, 3]}},
                            "status": {"n": 2}}, prev)
        assert new is not prev
        assert new["spec"] is prev["spec"]
        assert new["status"] is not prev["status"]

    def test_changed_list_rebuilt_unchanged_sibling_shared(self):
        prev = freeze({"a": [1, 2], "b": [3, 4]})
        new = freeze_delta({"a": [1, 2], "b": [3, 5]}, prev)
        assert new["a"] is prev["a"]
        assert new["b"] is not prev["b"]
        assert isinstance(new["b"], FrozenList)

    def test_scalar_type_change_not_shared(self):
        # 1 == True but they are different values to a serializer.
        prev = freeze({"a": True})
        new = freeze_delta({"a": 1}, prev)
        assert new is not prev
        assert new["a"] is not prev["a"]

    def test_result_is_deeply_frozen(self):
        new = freeze_delta({"a": {"b": [1]}}, None)
        assert isinstance(new, FrozenDict)
        assert isinstance(new["a"], FrozenDict)
        assert isinstance(new["a"]["b"], FrozenList)


class TestGenerationSemantics:
    """metadata.generation follows kube semantics: 1 at create, bumped
    only by spec changes — the hook GenerationChangedPredicate-style
    event filtering needs."""

    def setup_method(self):
        self.api = APIServer(clock=FakeClock())

    def teardown_method(self):
        self.api.close()

    def test_create_sets_generation_1(self):
        got = self.api.create(_cron("g1"))
        assert got["metadata"]["generation"] == 1

    def test_spec_change_bumps_generation(self):
        import copy

        self.api.create(_cron("g2"))
        cur = copy.deepcopy(
            self.api.get(CRON_API, "Cron", "default", "g2"))
        cur["spec"]["schedule"] = "5 * * * *"
        got = self.api.update(cur)
        assert got["metadata"]["generation"] == 2

    def test_metadata_only_change_keeps_generation(self):
        import copy

        self.api.create(_cron("g3"))
        cur = copy.deepcopy(
            self.api.get(CRON_API, "Cron", "default", "g3"))
        cur["metadata"]["labels"] = {"touched": "yes"}
        got = self.api.update(cur)
        assert got["metadata"]["generation"] == 1
        # status patches never move it either
        self.api.patch_status(
            CRON_API, "Cron", "default", "g3", {"n": "1"})
        after = self.api.get(CRON_API, "Cron", "default", "g3")
        assert after["metadata"]["generation"] == 1


class TestNoopStatusElision:
    def setup_method(self):
        self.api = APIServer(clock=FakeClock())

    def teardown_method(self):
        self.api.close()

    def test_identical_status_patch_is_a_no_write(self):
        self.api.create(_cron("s1"))
        first = self.api.patch_status(
            CRON_API, "Cron", "default", "s1", {"active": [], "n": "1"})
        rv = first["metadata"]["resourceVersion"]

        events = []
        self.api.add_watcher(events.append)
        again = self.api.patch_status(
            CRON_API, "Cron", "default", "s1", {"active": [], "n": "1"})
        self.api.flush()
        # same committed snapshot back, rv untouched, no watch event
        assert again is first
        assert again["metadata"]["resourceVersion"] == rv
        assert events == []

    def test_changed_status_still_commits(self):
        self.api.create(_cron("s2"))
        a = self.api.patch_status(
            CRON_API, "Cron", "default", "s2", {"n": "1"})
        b = self.api.patch_status(
            CRON_API, "Cron", "default", "s2", {"n": "2"})
        assert (int(b["metadata"]["resourceVersion"])
                > int(a["metadata"]["resourceVersion"]))


class _Recorder:
    def __init__(self):
        self.events = []

    def __call__(self, ev):
        self.events.append(ev)

    def types_for(self, name):
        return [
            e.type for e in self.events
            if e.object["metadata"]["name"] == name
        ]


class TestWatchDelivery:
    """Delivery contracts on both sides of the coalesce flag."""

    def setup_method(self):
        self.api = APIServer(clock=FakeClock())

    def teardown_method(self):
        self.api.close()

    def _update(self, name, schedule):
        import copy

        cur = copy.deepcopy(self.api.get(CRON_API, "Cron", "default", name))
        cur["spec"]["schedule"] = schedule
        self.api.update(cur)

    def test_plain_subscriber_sees_every_event_in_order(self):
        rec = _Recorder()
        self.api.add_watcher(rec)
        self.api.create(_cron("w1"))
        for m in (1, 2, 3):
            self._update("w1", f"{m} * * * *")
        self.api.delete(CRON_API, "Cron", "default", "w1")
        self.api.flush()
        assert rec.types_for("w1") == [
            "ADDED", "MODIFIED", "MODIFIED", "MODIFIED", "DELETED"]
        rvs = [int(e.object["metadata"]["resourceVersion"])
               for e in rec.events]
        assert rvs == sorted(rvs)

    def _plugged(self):
        """Block the dispatcher inside a sacrificial delivery so every
        event published while plugged lands in ONE drained batch."""
        in_plug = threading.Event()
        release = threading.Event()

        def plug_watcher(ev):
            if ev.object["metadata"]["name"] == "plug":
                in_plug.set()
                release.wait(5)

        self.api.add_watcher(plug_watcher)
        return in_plug, release

    def test_coalescing_subscriber_gets_latest_wins_modifieds(self):
        in_plug, release = self._plugged()
        plain = _Recorder()
        coal = _Recorder()
        self.api.add_watcher(plain)
        self.api.add_watcher(coal, coalesce=True)

        self.api.create(_cron("plug"))
        assert in_plug.wait(5)
        # Dispatcher is now stuck: a MODIFIED flap queues up behind it.
        self.api.create(_cron("w2"))
        for m in (1, 2, 3):
            self._update("w2", f"{m} * * * *")
        release.set()
        self.api.flush()

        # Strict subscriber: the full flap, in order.
        assert plain.types_for("w2") == [
            "ADDED", "MODIFIED", "MODIFIED", "MODIFIED"]
        # Coalescing subscriber: ADDED plus only the NEWEST modified.
        assert coal.types_for("w2") == ["ADDED", "MODIFIED"]
        mods = [e for e in coal.events
                if e.type == "MODIFIED"
                and e.object["metadata"]["name"] == "w2"]
        assert mods[0].object["spec"]["schedule"] == "3 * * * *"

    def test_added_and_deleted_never_elided(self):
        in_plug, release = self._plugged()
        coal = _Recorder()
        self.api.add_watcher(coal, coalesce=True)

        self.api.create(_cron("plug"))
        assert in_plug.wait(5)
        self.api.create(_cron("w3"))
        self._update("w3", "7 * * * *")
        self._update("w3", "8 * * * *")
        self.api.delete(CRON_API, "Cron", "default", "w3")
        release.set()
        self.api.flush()

        # First MODIFIED coalesced into the second; lifecycle edges kept.
        assert coal.types_for("w3") == ["ADDED", "MODIFIED", "DELETED"]

    def test_coalesced_deliveries_are_counted(self):
        class _Metrics:
            def __init__(self):
                self.values = {}

            def inc(self, series, amount=1.0):
                self.values[series] = self.values.get(series, 0.0) + amount

        metrics = _Metrics()
        self.api.instrument(metrics)
        in_plug, release = self._plugged()
        self.api.add_watcher(_Recorder(), coalesce=True)

        self.api.create(_cron("plug"))
        assert in_plug.wait(5)
        self.api.create(_cron("w4"))
        for m in (1, 2, 3):
            self._update("w4", f"{m} * * * *")
        release.set()
        self.api.flush()
        assert metrics.values.get("watch_events_coalesced_total") == 2.0


class TestSteadyStateZeroWrites:
    """The tentpole guarantee, end to end on the REAL stack: once a fired
    fleet has converged, a full list+reconcile sweep performs ZERO store
    writes — no rv movement, no commits counted."""

    def test_converged_sweep_commits_nothing(self):
        n = 20
        clock = FakeClock()
        api = APIServer(clock=clock)
        for i in range(n):
            api.create(_cron(f"steady-{i}"))

        created = threading.Semaphore(0)

        def count(ev):
            if ev.type == "ADDED" and ev.object.get("kind") == WL_KIND:
                created.release()

        api.add_watcher(count)
        mgr = Manager(api, max_concurrent_reconciles=2)
        rec = CronReconciler(api, metrics=mgr.metrics)
        mgr.add_controller(
            "cron", rec.reconcile, for_gvk=GVK_CRON,
            owns=default_scheme().workload_kinds(),
        )
        clock.advance(timedelta(minutes=61))
        mgr.start()
        try:
            for _ in range(n):
                assert created.acquire(timeout=10), "storm did not finish"
            # Quiesce: wait until the rv counter stops moving (manager
            # workers may still be flushing trailing status patches).
            import time as _time

            last = None
            for _ in range(100):
                cur = api._rv
                if cur == last:
                    break
                last = cur
                _time.sleep(0.05)

            rv_before = api._rv
            commits_before = _commits(mgr.metrics)
            for i in range(n):
                rec.reconcile("default", f"steady-{i}")
            assert api._rv == rv_before
            assert _commits(mgr.metrics) == commits_before
        finally:
            mgr.stop()
            api.close()


class TestListWorkloadsDedup:
    """A child that is both owner-referenced and label-matched must be
    listed exactly once (it used to be double-counted into
    status.active when the uid was absent)."""

    def setup_method(self):
        self.api = APIServer(clock=FakeClock())

    def teardown_method(self):
        self.api.close()

    def _reconciler(self):
        return CronReconciler(self.api)

    def test_owner_and_label_overlap_listed_once(self):
        from cron_operator_tpu.api.v1alpha1 import Cron

        committed = self.api.create(_cron("d1"))
        cron = Cron.from_dict(committed)
        self.api.create({
            "apiVersion": WL_API,
            "kind": WL_KIND,
            "metadata": {
                "name": "d1-child",
                "namespace": "default",
                "labels": {LABEL_CRON_NAME: "d1"},
                "ownerReferences": [{
                    "apiVersion": CRON_API, "kind": "Cron",
                    "name": "d1", "uid": committed["metadata"]["uid"],
                    "controller": True,
                }],
            },
            "spec": {},
        })
        rec = self._reconciler()
        from cron_operator_tpu.api.scheme import GVK

        got = rec._list_workloads(cron, GVK("kubeflow.org", "v1", WL_KIND))
        assert len(got) == 1

    def test_uid_less_objects_deduped_by_ns_name(self):
        """Even when snapshots carry no uid at all, (namespace, name)
        collapses duplicates across the two result sets."""
        from cron_operator_tpu.api.scheme import GVK
        from cron_operator_tpu.api.v1alpha1 import Cron

        committed = self.api.create(_cron("d2"))
        cron = Cron.from_dict(committed)
        self.api.create({
            "apiVersion": WL_API,
            "kind": WL_KIND,
            "metadata": {
                "name": "d2-child",
                "namespace": "default",
                "labels": {LABEL_CRON_NAME: "d2"},
            },
            "spec": {},
        })
        rec = self._reconciler()

        # Simulate a store whose owner index ALSO returns the labeled
        # child (snapshots without uid): dedup must still hold.
        labeled = self.api.list(
            WL_API, WL_KIND, namespace="default",
            label_selector={LABEL_CRON_NAME: "d2"},
        )
        stripped = []
        for w in labeled:
            import copy

            w = copy.deepcopy(w)
            w["metadata"].pop("uid", None)
            stripped.append(w)
        rec.api = _OwnerIndexStub(self.api, stripped)
        got = rec._list_workloads(cron, GVK("kubeflow.org", "v1", WL_KIND))
        assert len(got) == 1


class _OwnerIndexStub:
    """Pass-through to a real APIServer, with a canned dependents()."""

    def __init__(self, api, owned):
        self._api = api
        self._owned = owned

    def dependents(self, owner_uid, namespace=None):  # noqa: ARG002
        return list(self._owned)

    def __getattr__(self, name):
        return getattr(self._api, name)
