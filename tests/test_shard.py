"""Sharded control plane (runtime/shard.py): partition function pins,
router surface parity, WAL-shipping followers, and failover promotion."""

import json
import threading

import pytest

from cron_operator_tpu.runtime.kube import APIServer, NotFoundError
from cron_operator_tpu.runtime.manager import Metrics
from cron_operator_tpu.runtime.persistence import Persistence
from cron_operator_tpu.runtime.shard import (
    HASH_SPACE,
    FollowerReplica,
    OwnershipMap,
    ShardedControlPlane,
    ShardMetrics,
    ShardRouter,
    canonical_state,
    key_hash64,
    shard_dir,
    shard_index,
    split_key,
)
from cron_operator_tpu.utils.clock import FakeClock


def _cron(name, ns="default", spec=None):
    return {
        "apiVersion": "cron.tpu.example.com/v1alpha1",
        "kind": "TpuCronJob",
        "metadata": {"namespace": ns, "name": name},
        "spec": spec or {"schedule": "* * * * *"},
    }


CRON_GVK = ("cron.tpu.example.com/v1alpha1", "TpuCronJob")


class TestShardIndexPinned:
    """The partition hash is an ON-DISK FORMAT: shard WAL directories are
    named by index, so a hash change orphans every existing data dir.
    These vectors must never change; if this test fails, revert the hash
    — do not re-pin."""

    PAIRS = [
        ("default", "nightly-backup"),
        ("default", "bench-0"),
        ("default", "bench-1"),
        ("prod", "etl-hourly"),
        ("prod", "etl-hourly-28916560-abc12"),
        ("kube-system", "sweep"),
        ("team-a", "train-7b"),
        ("team-a", "train-7b-retry"),
        ("", ""),
        ("ns", "x" * 63),
    ]
    VECTORS = {
        1: [0, 0, 0, 0, 0, 0, 0, 0, 0, 0],
        4: [0, 0, 2, 0, 0, 1, 3, 2, 0, 3],
        16: [12, 4, 2, 4, 8, 13, 3, 2, 12, 11],
    }

    @pytest.mark.parametrize("n", [1, 4, 16])
    def test_pinned_vectors(self, n):
        got = [shard_index(ns, name, n) for ns, name in self.PAIRS]
        assert got == self.VECTORS[n]

    def test_range_and_determinism(self):
        for i in range(200):
            a = shard_index("default", f"obj-{i}", 4)
            assert 0 <= a < 4
            assert a == shard_index("default", f"obj-{i}", 4)

    def test_namespace_is_part_of_the_key(self):
        # "a/bc" vs "ab/c" must not collide via naive concatenation.
        hits = sum(
            shard_index("a", f"bc{i}", 16) == shard_index("ab", f"c{i}", 16)
            for i in range(64)
        )
        assert hits < 64


class TestOwnershipMapPinned:
    """Ownership-map cut points are an ON-DISK FORMAT (ownership.json
    names them; shard dirs are routed by them). Like the hash vectors
    above, these layouts must never change: a drift re-homes keys away
    from the shard dir that durably holds them."""

    PAIRS = TestShardIndexPinned.PAIRS

    HASHES = [
        0x4EA79E3EE3FC529C, 0x463382BB1554A144, 0x21993EEE1BC2B1A2,
        0x8B7073C7B8E9CF04, 0x056E9AAF8C452CB8, 0xDF83A9A244534F0D,
        0x5B2C26EEF198F593, 0xBBC9D66882B43A02, 0x35CA6884642C067C,
        0xED0D0303ECD6E85B,
    ]

    def test_pinned_key_hashes(self):
        assert [key_hash64(ns, n) for ns, n in self.PAIRS] == self.HASHES

    @pytest.mark.parametrize("n", [1, 4, 16])
    def test_boot_map_is_exactly_the_modulo_hash(self, n):
        m = OwnershipMap.boot(n)
        assert m.epoch == 0 and m.n_shards == n
        for ns, name in self.PAIRS:
            assert m.owner(ns, name) == shard_index(ns, name, n)
        for i in range(200):
            assert m.owner("default", f"obj-{i}") == shard_index(
                "default", f"obj-{i}", n
            )

    def test_pinned_split_1_to_2_layout(self):
        m, plan = OwnershipMap.boot(1).split(0)
        assert plan["mid"] == 0x8000000000000000
        assert plan["end"] == HASH_SPACE
        assert (plan["parent"], plan["child"], plan["epoch"]) == (0, 1, 1)
        assert m.epoch == 1 and m.n_shards == 2
        assert [m.owner(ns, n) for ns, n in self.PAIRS] == [
            0, 0, 0, 1, 0, 1, 0, 1, 0, 1,
        ]

    def test_pinned_second_split_layouts(self):
        two, _ = OwnershipMap.boot(1).split(0)
        # splitting the PARENT again quarters the lower half...
        three, plan = two.split(0)
        assert plan["mid"] == 0x4000000000000000 and plan["child"] == 2
        assert [three.owner(ns, n) for ns, n in self.PAIRS] == [
            2, 2, 0, 1, 0, 1, 2, 1, 0, 1,
        ]
        # ...splitting the CHILD quarters the upper half instead.
        threeb, planb = two.split(1)
        assert planb["mid"] == 0xC000000000000000 and planb["child"] == 2
        assert [threeb.owner(ns, n) for ns, n in self.PAIRS] == [
            0, 0, 0, 1, 0, 2, 0, 1, 0, 2,
        ]

    def test_pinned_boot4_split_touches_one_class_only(self):
        m, plan = OwnershipMap.boot(4).split(2)
        assert plan["class_id"] == 2 and plan["child"] == 4
        assert plan["mid"] == 0x8000000000000000
        got = [m.owner(ns, n) for ns, n in self.PAIRS]
        assert got == [0, 0, 2, 0, 0, 1, 3, 4, 0, 3]
        # every key OUTSIDE class 2 still routes by the modulo hash
        for ns, name in self.PAIRS:
            if key_hash64(ns, name) % 4 != 2:
                assert m.owner(ns, name) == shard_index(ns, name, 4)
        assert m.ranges_for(4) == [{
            "class": 2,
            "start": "0x8000000000000000",
            "end": "0x10000000000000000",
            "owner": 4,
        }]

    def test_doc_roundtrip_and_save_load(self, tmp_path):
        m, _ = OwnershipMap.boot(4).split(2)
        m2, _ = m.split(4)
        doc = m2.to_doc()
        assert doc["version"] == 1
        back = OwnershipMap.from_doc(json.loads(json.dumps(doc)))
        assert back.classes == m2.classes
        assert back.epoch == m2.epoch and back.n_boot == m2.n_boot
        path = str(tmp_path / "ownership.json")
        assert OwnershipMap.load(path) is None
        m2.save(path)
        loaded = OwnershipMap.load(path)
        assert loaded is not None and loaded.classes == m2.classes

    def test_split_key_follows_controller_owner(self):
        child = _cron("etl-hourly-28916560-abc12", ns="prod")
        child["metadata"]["ownerReferences"] = [{
            "apiVersion": "cron.tpu.example.com/v1alpha1",
            "kind": "TpuCronJob", "name": "etl-hourly", "uid": "u-1",
            "controller": True,
        }]
        assert split_key(child) == ("prod", "etl-hourly")
        assert split_key(_cron("standalone")) == ("default", "standalone")
        m, _ = OwnershipMap.boot(1).split(0)
        # the root hashes into the moved range; the child's OWN hash
        # does not — yet both must land on the new shard together.
        assert key_hash64("prod", "etl-hourly-28916560-abc12") < (
            0x8000000000000000
        )
        assert m.owner_of(child) == 1 == m.owner("prod", "etl-hourly")

    def test_validation_rejects_malformed_layouts(self):
        with pytest.raises(ValueError):
            OwnershipMap(2, [[(0, 0)]])  # class count mismatch
        with pytest.raises(ValueError):
            OwnershipMap(1, [[(1, 0)]])  # does not start at 0
        with pytest.raises(ValueError):
            OwnershipMap(1, [[(0, 0), (5, 1), (5, 2)]])  # not increasing
        with pytest.raises(ValueError):
            OwnershipMap(1, [[(0, 0), (HASH_SPACE, 1)]])  # out of space
        with pytest.raises(ValueError):
            OwnershipMap.from_doc({"version": 9})
        with pytest.raises(ValueError):
            OwnershipMap.boot(2).split(7)  # owns no range


class TestShardRouter:
    def _plane(self, n=4):
        clock = FakeClock()
        stores = [APIServer(clock) for _ in range(n)]
        return ShardRouter(stores), stores

    def test_create_routes_to_hash_home(self):
        router, stores = self._plane(4)
        for i in range(40):
            router.create(_cron(f"c-{i}"))
        for i in range(40):
            home = stores[shard_index("default", f"c-{i}", 4)]
            assert home.get_frozen(*CRON_GVK, "default", f"c-{i}") is not None
        # distributed, not piled on one shard
        sizes = [len(s) for s in stores]
        assert sum(sizes) == 40 and max(sizes) < 40

    def test_list_fans_in_and_rv_sums(self):
        router, stores = self._plane(4)
        for i in range(20):
            router.create(_cron(f"c-{i}"))
        objs, rv = router.list_with_rv(*CRON_GVK)
        assert len(objs) == 20
        assert int(rv) == sum(int(getattr(s, "_rv")) for s in stores)
        assert router._rv == int(rv)

    def test_rv_bracketing_detects_zero_writes(self):
        router, _ = self._plane(4)
        for i in range(10):
            router.create(_cron(f"c-{i}"))
        before = router._rv
        router.list(*CRON_GVK)
        for i in range(10):
            router.get_frozen(*CRON_GVK, "default", f"c-{i}")
        assert router._rv == before
        router.patch_status(*CRON_GVK, "default", "c-0", {"phase": "Active"})
        assert router._rv == before + 1
        # no-op elision must hold through the router too
        router.patch_status(*CRON_GVK, "default", "c-0", {"phase": "Active"})
        assert router._rv == before + 1

    def test_probe_fallback_finds_off_home_children(self):
        # A reconciler creates children directly on its OWN shard store —
        # the child's hash home is usually a different shard. The router
        # must still find it.
        router, stores = self._plane(4)
        owner_shard = stores[1]
        child = _cron("child-lives-with-owner", spec={"x": 1})
        assert shard_index("default", "child-lives-with-owner", 4) != 1
        owner_shard.create(child)
        got = router.get(*CRON_GVK, "default", "child-lives-with-owner")
        assert got["spec"] == {"x": 1}
        router.patch_status(
            *CRON_GVK, "default", "child-lives-with-owner", {"ok": True}
        )
        assert owner_shard.get_frozen(
            *CRON_GVK, "default", "child-lives-with-owner"
        )["status"] == {"ok": True}
        router.delete(*CRON_GVK, "default", "child-lives-with-owner")
        assert router.try_get(
            *CRON_GVK, "default", "child-lives-with-owner"
        ) is None

    def test_missing_object_raises_not_found(self):
        router, _ = self._plane(4)
        with pytest.raises(NotFoundError):
            router.get(*CRON_GVK, "default", "ghost")
        assert router.try_get(*CRON_GVK, "default", "ghost") is None

    def test_watch_fans_out_from_every_shard(self):
        router, _ = self._plane(4)
        seen = []
        lock = threading.Lock()

        def watcher(ev):
            with lock:
                seen.append((ev.type, ev.object["metadata"]["name"]))

        router.add_watcher(watcher, coalesce=True)
        for i in range(12):
            router.create(_cron(f"w-{i}"))
        assert router.flush(timeout=5.0)
        with lock:
            assert sorted(n for t, n in seen if t == "ADDED") == sorted(
                f"w-{i}" for i in range(12)
            )

    def test_len_events_all_objects_aggregate(self):
        router, _ = self._plane(2)
        obj = router.create(_cron("ev-target"))
        router.record_event(obj, "Normal", "Fired", "hello")
        assert len(router) >= 1
        assert any(e.reason == "Fired" for e in router.events())
        names = {
            o["metadata"]["name"]
            for o in router.all_objects()
            if o.get("kind") == "TpuCronJob"
        }
        assert "ev-target" in names
        router.close()


class TestShardMetrics:
    def test_label_injection_bare_and_labeled(self):
        m = Metrics()
        sm = ShardMetrics(m, 3)
        sm.inc("wal_records_total")
        sm.inc('workqueue_adds_total{name="cron"}', 2.0)
        sm.set('workqueue_depth{name="cron"}', 5.0)
        sm.observe("reconcile_seconds", 0.5, buckets=(0.1, 1.0))
        assert m.get('wal_records_total{shard="3"}') == 1.0
        assert m.get('workqueue_adds_total{name="cron",shard="3"}') == 2.0
        assert m.gauge('workqueue_depth{name="cron",shard="3"}') == 5.0
        assert m.histogram('reconcile_seconds{shard="3"}') is not None
        # the per-shard view reads back its own series
        assert sm.get("wal_records_total") == 1.0
        assert sm.gauge('workqueue_depth{name="cron"}') == 5.0

    def test_two_shards_share_one_registry_without_collision(self):
        m = Metrics()
        a, b = ShardMetrics(m, 0), ShardMetrics(m, 1)
        a.inc("apiserver_commits_total")
        b.inc("apiserver_commits_total")
        b.inc("apiserver_commits_total")
        assert m.get('apiserver_commits_total{shard="0"}') == 1.0
        assert m.get('apiserver_commits_total{shard="1"}') == 2.0

    def test_registry_wide_calls_delegate(self):
        m = Metrics()
        sm = ShardMetrics(m, 0)
        sm.inc("x_total")
        assert "x_total" in sm.render_prometheus()
        assert sm.snapshot() == m.snapshot()


class TestFollowerReplication:
    def test_follower_tracks_leader_through_wal_shipping(self, tmp_path):
        clock = FakeClock()
        api = APIServer(clock)
        pers = Persistence(str(tmp_path), flush_interval_s=0)
        pers.start(api)
        follower = FollowerReplica(clock)
        pers.attach_follower(follower)
        for i in range(10):
            api.create(_cron(f"f-{i}"))
        api.patch_status(*CRON_GVK, "default", "f-0", {"phase": "Active"})
        api.delete(*CRON_GVK, "default", "f-9")
        pers.flush()
        assert follower.lag_bytes == 0
        assert len(follower.store) == len(api)
        assert follower.store.get_frozen(
            *CRON_GVK, "default", "f-0"
        )["status"] == {"phase": "Active"}
        assert follower.store.get_frozen(*CRON_GVK, "default", "f-9") is None
        assert (CRON_GVK[0], CRON_GVK[1], "default", "f-9") in (
            follower.deleted_keys
        )
        # I6, the exact promotion precondition: follower state equals an
        # independent replay of the on-disk bytes.
        replay = Persistence(str(tmp_path)).recover()
        assert follower.state() == canonical_state(replay.objects, replay.rv)
        pers.close()
        api.close()
        follower.store.close()

    def test_partial_line_buffered_never_applied(self):
        follower = FollowerReplica(FakeClock())
        rec = json.dumps(
            {"op": "put", "verb": "create", "rv": 1, "obj": _cron("torn")}
        ).encode() + b"\n"
        follower.apply_bytes(rec[: len(rec) // 2])
        assert len(follower.store) == 0
        assert follower.lag_bytes == len(rec) // 2
        follower.apply_bytes(rec[len(rec) // 2:])
        assert len(follower.store) == 1
        assert follower.lag_bytes == 0
        # a torn FINAL fragment (leader died mid-record) is never applied
        follower.apply_bytes(b'{"op":"put","rv":2,"obj":{"apiVers')
        assert len(follower.store) == 1

    def test_replicated_rvs_match_leader(self, tmp_path):
        api = APIServer(FakeClock())
        pers = Persistence(str(tmp_path), flush_interval_s=0)
        pers.start(api)
        follower = FollowerReplica()
        pers.attach_follower(follower)
        api.create(_cron("rv-check"))
        api.patch_status(*CRON_GVK, "default", "rv-check", {"n": 1})
        pers.flush()
        lead = api.get_frozen(*CRON_GVK, "default", "rv-check")
        repl = follower.store.get_frozen(*CRON_GVK, "default", "rv-check")
        assert (repl["metadata"]["resourceVersion"]
                == lead["metadata"]["resourceVersion"])
        assert getattr(follower.store, "_rv") == getattr(api, "_rv")
        pers.close()
        api.close()


class TestShardedControlPlaneFailover:
    def test_promote_follower_after_leader_kill(self, tmp_path):
        plane = ShardedControlPlane(
            n_shards=2, replicas=1, data_dir=str(tmp_path),
            clock=FakeClock(), metrics=Metrics(), flush_interval_s=0,
        )
        try:
            for i in range(30):
                plane.router.create(_cron(f"p-{i}"))
            for s in plane.shards:
                s.persistence.flush()
            victim = plane.shards[0]
            n_before = len(victim.store)
            victim.persistence.kill()
            report = plane.promote_follower(0)
            assert report["i6_ok"] is True
            assert report["objects"] == n_before
            assert victim.failovers == 1
            # the promoted store serves the partition through the router
            assert len(plane.router) == 30
            plane.router.create(_cron("after-failover"))
            assert len(plane.router) == 31
            # promoted leader is durable again AND replicated again
            assert victim.persistence is not None
            assert not victim.persistence.dead
            assert victim.follower is not None
            victim.persistence.flush()
            assert len(victim.follower.store) == len(victim.store)
            assert plane.metrics.get(
                'shard_failovers_total{shard="0"}'
            ) == 1.0
        finally:
            plane.close()

    def test_promoted_state_survives_restart(self, tmp_path):
        clock = FakeClock()
        plane = ShardedControlPlane(
            n_shards=2, replicas=1, data_dir=str(tmp_path),
            clock=clock, flush_interval_s=0,
        )
        for i in range(12):
            plane.router.create(_cron(f"r-{i}"))
        for s in plane.shards:
            s.persistence.flush()
        plane.shards[1].persistence.kill()
        plane.promote_follower(1)
        plane.router.create(_cron("written-after-promotion"))
        state = canonical_state(
            plane.router.all_objects(), plane.router._rv
        )
        plane.close()

        reopened = ShardedControlPlane(
            n_shards=2, data_dir=str(tmp_path),
            clock=clock, flush_interval_s=0,
        )
        try:
            assert reopened.recovered_any
            assert canonical_state(
                reopened.router.all_objects(), reopened.router._rv
            ) == state
        finally:
            reopened.close()

    def test_replicas_require_data_dir(self):
        with pytest.raises(ValueError):
            ShardedControlPlane(n_shards=2, replicas=1, data_dir=None)

    def test_shard_dirs_are_per_index(self, tmp_path):
        plane = ShardedControlPlane(
            n_shards=3, data_dir=str(tmp_path), flush_interval_s=0
        )
        try:
            for i in range(3):
                assert plane.shards[i].data_dir == shard_dir(str(tmp_path), i)
                assert plane.shards[i].data_dir.endswith(f"shard-{i}")
        finally:
            plane.close()
