"""ClusterAPIServer tests against an in-process fake kube-apiserver.

The fake speaks just enough of the Kubernetes REST protocol (typed paths,
label selectors, status subresource merge-patch, streaming watch with an
initial resourceVersion) to prove the adapter's request shapes are right —
the same role envtest's real apiserver plays for the reference
(SURVEY.md §4), scaled to what stdlib can host.

The capstone test runs the REAL manager + reconciler against the fake
cluster: a Cron CR "applied to the cluster" leads to a JAXJob POST — the
production path the deploy manifests promise.
"""

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

import pytest

from cron_operator_tpu.api.scheme import GVK_CRON, default_scheme
from cron_operator_tpu.controller import CronReconciler
from cron_operator_tpu.runtime import Manager
from cron_operator_tpu.runtime.cluster import ClusterAPIServer, ClusterConfig
from cron_operator_tpu.runtime.kube import NotFoundError


class FakeKube:
    """In-memory store keyed the way the REST paths address it."""

    def __init__(self):
        self.lock = threading.Lock()
        self.objects = {}  # (path_prefix, name) -> obj
        self.rv = 0
        self.watchers = []  # list of (path_prefix, queue-like list, event)
        self.requests = []  # (method, path) log

    def next_rv(self):
        self.rv += 1
        return str(self.rv)


def _make_handler(store: FakeKube):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *a):
            pass

        def _send(self, code, payload):
            data = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def _key(self):
            """Split /apis/group/v1/namespaces/ns/plural[/name[/status]]."""
            parsed = urlparse(self.path)
            parts = [p for p in parsed.path.split("/") if p]
            sub = None
            if parts and parts[-1] == "status":
                sub = "status"
                parts = parts[:-1]
            return parsed, parts, sub

        @staticmethod
        def _prefix_matches(stored_prefix, watch_prefix):
            """Cluster-wide collections (no /namespaces/<ns>/ segment) match
            every namespace's stored prefix for the same group+plural."""
            if stored_prefix == watch_prefix:
                return True
            wparts = watch_prefix.split("/")
            sparts = stored_prefix.split("/")
            if "namespaces" in wparts or "namespaces" not in sparts:
                return False
            return (
                sparts[: len(wparts) - 1] == wparts[:-1]
                and sparts[-1] == wparts[-1]
            )

        def _notify(self, etype, prefix, obj):
            with store.lock:
                for wprefix, sink, event in store.watchers:
                    if self._prefix_matches(prefix, wprefix):
                        sink.append({"type": etype, "object": obj})
                        event.set()

        def do_GET(self):  # noqa: N802
            parsed, parts, _ = self._key()
            store.requests.append(("GET", parsed.path))
            q = parse_qs(parsed.query)
            if q.get("watch") == ["true"]:
                return self._serve_watch(parsed, parts)
            # Disambiguate object vs collection by path arity:
            # /api/v1/namespaces/ns/pods/name        (6) vs .../pods  (5)
            # /apis/g/v/namespaces/ns/plural/name    (7) vs          (6)
            is_object = (parts[0] == "api" and len(parts) == 6) or (
                parts[0] == "apis" and len(parts) == 7
            )
            with store.lock:
                if is_object:
                    prefix, name = "/".join(parts[:-1]), parts[-1]
                    obj = store.objects.get((prefix, name))
                    if obj is None:
                        return self._send(
                            404, {"kind": "Status", "reason": "NotFound"}
                        )
                    return self._send(200, obj)
                # collection LIST (namespaced or cluster-wide)
                prefix = "/".join(parts)
                sel = q.get("labelSelector", [None])[0]
                items = []
                for (p, _), o in store.objects.items():
                    if not self._prefix_matches(p, prefix):
                        continue
                    if sel:
                        labels = (o.get("metadata") or {}).get("labels") or {}
                        want = dict(
                            kv.split("=", 1) for kv in sel.split(",")
                        )
                        if any(labels.get(k) != v for k, v in want.items()):
                            continue
                    items.append(o)
                return self._send(200, {
                    "kind": "List",
                    "metadata": {"resourceVersion": str(store.rv)},
                    "items": items,
                })

        def _serve_watch(self, parsed, parts):
            prefix = "/".join(parts)
            sink, event = [], threading.Event()
            q = parse_qs(parsed.query)
            from_rv = int(q.get("resourceVersion", ["0"])[0] or 0)
            with store.lock:
                # Replay anything newer than the requested resourceVersion
                # (kube watch semantics — events between LIST and WATCH
                # registration must not be lost).
                for (p, _), o in store.objects.items():
                    orv = int((o.get("metadata") or {}).get(
                        "resourceVersion", 0
                    ))
                    if self._prefix_matches(p, prefix) and orv > from_rv:
                        sink.append({"type": "ADDED", "object": o})
                if sink:
                    event.set()
                store.watchers.append((prefix, sink, event))
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()
            try:
                deadline = time.time() + 5.0
                while time.time() < deadline:
                    event.wait(0.1)
                    with store.lock:
                        pending, sink[:] = sink[:], []
                        event.clear()
                    for evt in pending:
                        line = (json.dumps(evt) + "\n").encode()
                        self.wfile.write(
                            f"{len(line):x}\r\n".encode() + line + b"\r\n"
                        )
                        self.wfile.flush()
                self.wfile.write(b"0\r\n\r\n")
            except (BrokenPipeError, ConnectionResetError):
                pass

        def _read_body(self):
            n = int(self.headers.get("Content-Length", 0))
            return json.loads(self.rfile.read(n)) if n else {}

        def do_POST(self):  # noqa: N802
            parsed, parts, _ = self._key()
            store.requests.append(("POST", parsed.path))
            obj = self._read_body()
            prefix = "/".join(parts)
            name = (obj.get("metadata") or {}).get("name")
            with store.lock:
                if (prefix, name) in store.objects:
                    return self._send(409, {
                        "kind": "Status", "reason": "AlreadyExists",
                        "message": f"{name} exists",
                    })
                obj.setdefault("metadata", {})["resourceVersion"] = (
                    store.next_rv()
                )
                obj["metadata"].setdefault("uid", f"uid-{store.rv}")
                obj["metadata"].setdefault(
                    "creationTimestamp", "2026-07-29T00:00:00Z"
                )
                store.objects[(prefix, name)] = obj
            self._notify("ADDED", prefix, obj)
            return self._send(201, obj)

        def do_PUT(self):  # noqa: N802
            parsed, parts, _ = self._key()
            store.requests.append(("PUT", parsed.path))
            obj = self._read_body()
            prefix, name = "/".join(parts[:-1]), parts[-1]
            with store.lock:
                if (prefix, name) not in store.objects:
                    return self._send(404, {"kind": "Status",
                                            "reason": "NotFound"})
                obj.setdefault("metadata", {})["resourceVersion"] = (
                    store.next_rv()
                )
                store.objects[(prefix, name)] = obj
            self._notify("MODIFIED", prefix, obj)
            return self._send(200, obj)

        def do_PATCH(self):  # noqa: N802
            parsed, parts, sub = self._key()
            store.requests.append(("PATCH", parsed.path))
            patch = self._read_body()
            prefix, name = "/".join(parts[:-1]), parts[-1]
            with store.lock:
                obj = store.objects.get((prefix, name))
                if obj is None:
                    return self._send(404, {"kind": "Status",
                                            "reason": "NotFound"})
                if sub == "status":
                    obj["status"] = patch.get("status")
                else:
                    obj.update(patch)
                obj["metadata"]["resourceVersion"] = store.next_rv()
            self._notify("MODIFIED", prefix, obj)
            return self._send(200, obj)

        def do_DELETE(self):  # noqa: N802
            parsed, parts, _ = self._key()
            store.requests.append(("DELETE", parsed.path))
            prefix, name = "/".join(parts[:-1]), parts[-1]
            with store.lock:
                obj = store.objects.pop((prefix, name), None)
            if obj is None:
                return self._send(404, {"kind": "Status", "reason": "NotFound"})
            self._notify("DELETED", prefix, obj)
            return self._send(200, {"kind": "Status", "status": "Success"})

    return Handler


@pytest.fixture
def fake_cluster():
    store = FakeKube()
    server = ThreadingHTTPServer(("127.0.0.1", 0), _make_handler(store))
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    yield store, f"http://127.0.0.1:{server.server_port}"
    server.shutdown()


@pytest.fixture
def capi(fake_cluster):
    _, url = fake_cluster
    api = ClusterAPIServer(ClusterConfig(url), scheme=default_scheme())
    yield api
    api.stop()


CRON = {
    "apiVersion": "apps.kubedl.io/v1alpha1",
    "kind": "Cron",
    "metadata": {"name": "c1", "namespace": "default",
                 "labels": {"team": "ml"}},
    "spec": {"schedule": "@every 1s", "template": {"workload": {
        "apiVersion": "kubeflow.org/v1", "kind": "JAXJob",
        "spec": {"replicaSpecs": {"Worker": {"replicas": 1}}},
    }}},
}


class TestClusterCRUD:
    def test_create_get_roundtrip(self, capi):
        capi.create(dict(CRON))
        got = capi.get("apps.kubedl.io/v1alpha1", "Cron", "default", "c1")
        assert got["spec"]["schedule"] == "@every 1s"
        assert got["metadata"]["resourceVersion"]

    def test_typed_path_shapes(self, capi, fake_cluster):
        store, _ = fake_cluster
        capi.create(dict(CRON))
        capi.get("apps.kubedl.io/v1alpha1", "Cron", "default", "c1")
        assert (
            "POST",
            "/apis/apps.kubedl.io/v1alpha1/namespaces/default/crons",
        ) in store.requests
        # core-group kinds use /api/v1
        capi.create({
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": "p1", "namespace": "default"},
        })
        assert ("POST", "/api/v1/namespaces/default/pods") in store.requests

    def test_not_found_and_already_exists(self, capi):
        with pytest.raises(NotFoundError):
            capi.get("apps.kubedl.io/v1alpha1", "Cron", "default", "nope")
        capi.create(dict(CRON))
        from cron_operator_tpu.runtime.kube import AlreadyExistsError

        with pytest.raises(AlreadyExistsError):
            capi.create(dict(CRON))

    def test_list_label_selector(self, capi):
        capi.create(dict(CRON))
        other = json.loads(json.dumps(CRON))
        other["metadata"]["name"] = "c2"
        other["metadata"]["labels"] = {"team": "infra"}
        capi.create(other)
        ml = capi.list("apps.kubedl.io/v1alpha1", "Cron", "default",
                       label_selector={"team": "ml"})
        assert [c["metadata"]["name"] for c in ml] == ["c1"]
        # list items get apiVersion/kind restored
        assert ml[0]["apiVersion"] == "apps.kubedl.io/v1alpha1"

    def test_patch_status_merge(self, capi, fake_cluster):
        store, _ = fake_cluster
        capi.create(dict(CRON))
        capi.patch_status(
            "apps.kubedl.io/v1alpha1", "Cron", "default", "c1",
            {"lastScheduleTime": "2026-07-29T12:00:00Z"},
        )
        assert (
            "PATCH",
            "/apis/apps.kubedl.io/v1alpha1/namespaces/default/crons/c1/status",
        ) in store.requests
        got = capi.get("apps.kubedl.io/v1alpha1", "Cron", "default", "c1")
        assert got["status"]["lastScheduleTime"] == "2026-07-29T12:00:00Z"

    def test_delete(self, capi):
        capi.create(dict(CRON))
        capi.delete("apps.kubedl.io/v1alpha1", "Cron", "default", "c1")
        assert capi.try_get(
            "apps.kubedl.io/v1alpha1", "Cron", "default", "c1"
        ) is None

    def test_record_event(self, capi, fake_cluster):
        store, _ = fake_cluster
        capi.record_event(dict(CRON), "Warning", "FailedCreate", "boom")
        events = [
            o for (p, _), o in store.objects.items() if p.endswith("events")
        ]
        assert len(events) == 1
        assert events[0]["reason"] == "FailedCreate"
        assert events[0]["involvedObject"]["name"] == "c1"


class TestClusterReconcileLoop:
    """The production path: real Manager + CronReconciler over the cluster
    adapter — a Cron applied to the 'cluster' produces a JAXJob there."""

    def test_cron_cr_creates_workload_in_cluster(self, capi, fake_cluster):
        store, _ = fake_cluster
        mgr = Manager(capi, max_concurrent_reconciles=2)
        rec = CronReconciler(capi)
        mgr.add_controller(
            "cron", rec.reconcile, for_gvk=GVK_CRON,
            owns=default_scheme().workload_kinds(),
        )
        mgr.start()
        capi.start_watches([GVK_CRON] + default_scheme().workload_kinds())
        try:
            capi.create(dict(CRON))
            deadline = time.time() + 10.0
            jobs = []
            while time.time() < deadline and not jobs:
                jobs = capi.list("kubeflow.org/v1", "JAXJob",
                                 namespace="default")
                time.sleep(0.1)
            assert jobs, "reconciler never created the JAXJob in the cluster"
            job = jobs[0]
            assert job["metadata"]["labels"]["kubedl.io/cron-name"] == "c1"
            owner = job["metadata"]["ownerReferences"][0]
            assert owner["kind"] == "Cron" and owner["name"] == "c1"
            # status was patched through the subresource
            deadline = time.time() + 5.0
            last = None
            while time.time() < deadline and last is None:
                cron = capi.get(
                    "apps.kubedl.io/v1alpha1", "Cron", "default", "c1"
                )
                last = (cron.get("status") or {}).get("lastScheduleTime")
                time.sleep(0.1)
            assert last is not None
        finally:
            mgr.stop()
            capi.stop()

    def test_cluster_mode_applies_tpu_admission(self, capi, fake_cluster):
        """VERDICT r2 #1: the JAXJob POSTed to the cluster must already carry
        the TPU scheduling metadata — the admission seam lives in the
        controller (``_new_workload_from_template`` → ``inject_tpu_topology``),
        not only in the embedded LocalExecutor."""
        store, _ = fake_cluster
        cron = json.loads(json.dumps(CRON))
        cron["metadata"]["name"] = "ctpu"
        tpl = cron["spec"]["template"]["workload"]
        tpl["metadata"] = {"annotations": {
            "tpu.kubedl.io/accelerator": "v5e",
            "tpu.kubedl.io/topology": "4x4",
            "tpu.kubedl.io/param.lr": "0.001",
        }}
        mgr = Manager(capi, max_concurrent_reconciles=2)
        rec = CronReconciler(capi)
        mgr.add_controller(
            "cron", rec.reconcile, for_gvk=GVK_CRON,
            owns=default_scheme().workload_kinds(),
        )
        mgr.start()
        capi.start_watches([GVK_CRON] + default_scheme().workload_kinds())
        try:
            capi.create(cron)
            deadline = time.time() + 10.0
            jobs = []
            while time.time() < deadline and not jobs:
                jobs = capi.list("kubeflow.org/v1", "JAXJob",
                                 namespace="default")
                time.sleep(0.1)
            assert jobs, "reconciler never created the JAXJob in the cluster"
            job = jobs[0]
            worker = job["spec"]["replicaSpecs"]["Worker"]
            # v5e 4x4 = 16 chips = 4 hosts × 4 chips
            assert worker["replicas"] == 4
            pod_spec = worker["template"]["spec"]
            assert pod_spec["nodeSelector"] == {
                "cloud.google.com/gke-tpu-accelerator": "tpu-v5-lite-podslice",
                "cloud.google.com/gke-tpu-topology": "4x4",
            }
            container = pod_spec["containers"][0]
            for section in ("requests", "limits"):
                assert container["resources"][section]["google.com/tpu"] == "4"
            env = {e["name"]: e for e in container["env"]}
            assert env["JAX_NUM_PROCESSES"]["value"] == "4"
            assert env["JAX_COORDINATOR_ADDRESS"]["value"].endswith(":8476")
            assert "valueFrom" in env["JAX_PROCESS_ID"]
            assert env["TPU_PARAM_LR"]["value"] == "0.001"
            assert (
                job["metadata"]["annotations"]["tpu.kubedl.io/gang-size"]
                == "4"
            )
        finally:
            mgr.stop()
            capi.stop()


class TestClientFlowControl:
    """client-go flowcontrol parity: --qps/--burst actually rate-limit
    the kube client (reference wires them into rest.Config at
    start.go:152-154; previously these flags were accepted but unused)."""

    def test_token_bucket_burst_then_throttle(self):
        import time

        from cron_operator_tpu.runtime.cluster import TokenBucket

        tb = TokenBucket(qps=20, burst=3)
        t0 = time.monotonic()
        for _ in range(3):
            tb.acquire()  # burst: no token refill needed
        burst_elapsed = time.monotonic() - t0

        t0 = time.monotonic()
        for _ in range(4):
            tb.acquire()  # empty bucket: ~1/20 s each
        throttled = time.monotonic() - t0
        # Lower-bound assertions only (upper bounds flake on loaded CI):
        # the throttled phase must wait, and must be slower than the burst
        # phase by at least one refill interval.
        assert throttled >= 0.15, f"not throttled: {throttled:.3f}s"
        assert throttled > burst_elapsed + 0.05, (burst_elapsed, throttled)

    def test_token_bucket_sleeps_outside_the_lock(self):
        # Regression: acquire() used to hold the bucket lock across its
        # sleep, serializing N waiting threads into N full sleeps. With
        # reservation-style debt the waits overlap: two threads draining
        # an empty qps=4 bucket reserve slots at +0.25s and +0.5s and
        # sleep CONCURRENTLY, so wall time is ~0.5s — not the ~0.75s+ a
        # lock-held sleep would force (0.25 then 0.5 back to back).
        import threading
        import time

        from cron_operator_tpu.runtime.cluster import TokenBucket

        tb = TokenBucket(qps=4, burst=1)
        tb.acquire()  # drain the single burst token

        start = time.monotonic()
        threads = [threading.Thread(target=tb.acquire) for _ in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=5.0)
        elapsed = time.monotonic() - start
        assert not any(t.is_alive() for t in threads)
        # Both reservations honored (real throttling)...
        assert elapsed >= 0.45, f"not throttled: {elapsed:.3f}s"
        # ...but overlapped, not serialized behind the lock.
        assert elapsed < 0.70, f"sleeps serialized: {elapsed:.3f}s"

    def test_requests_are_limited_end_to_end(self):
        import time

        from cron_operator_tpu.runtime.apiserver_http import HTTPAPIServer
        from cron_operator_tpu.runtime.cluster import (
            ClusterAPIServer,
            ClusterConfig,
        )

        srv = HTTPAPIServer()
        srv.start()
        try:
            capi = ClusterAPIServer(
                ClusterConfig(srv.url, qps=20, burst=2),
                scheme=default_scheme(),
            )
            t0 = time.monotonic()
            for _ in range(6):
                capi.list("apps.kubedl.io/v1alpha1", "Cron", "default")
            elapsed = time.monotonic() - t0
            capi.stop()
            # 2 burst + 4 throttled at 20/s ≥ 0.2 s minus scheduling slop.
            assert elapsed >= 0.15, f"flow control inactive: {elapsed:.3f}s"
        finally:
            srv.stop()
