"""Fleet observatory (telemetry/observatory.py): deadline-SLO accounting
folded from synthetic audit records, queue-wait distributions per
priority class, utilization integration over explicit fleet samples, the
rv-bracketed zero-store-write property, JSONL rollups + hooks, and the
ThroughputMatrix save/load sidecar round-trip."""

from __future__ import annotations

import json

from cron_operator_tpu.runtime.fleet import (
    FleetScheduler,
    ThroughputMatrix,
    parse_pool,
)
from cron_operator_tpu.runtime.kube import APIServer
from cron_operator_tpu.runtime.manager import Metrics
from cron_operator_tpu.telemetry import AuditJournal, FleetObservatory

CRON_KEY = "apps.kubedl.io/v1alpha1/Cron/default/demo"


def _wired(**kw):
    m = Metrics()
    j = AuditJournal(metrics=m)
    obs = FleetObservatory(metrics=m, **kw)
    j.attach_observer(obs.on_record)
    return m, j, obs


def _job(name, wclass="w", tenant=None, priority=None):
    ann = {"tpu.kubedl.io/workload-class": wclass}
    if tenant is not None:
        ann["tpu.kubedl.io/tenant"] = tenant
    if priority is not None:
        ann["tpu.kubedl.io/priority"] = str(priority)
    return {
        "apiVersion": "kubeflow.org/v1", "kind": "JAXJob",
        "metadata": {
            "namespace": "default", "name": name, "annotations": ann,
        },
        "spec": {"replicaSpecs": {"Worker": {"replicas": 1}}},
    }


class TestDeadlineSLO:
    def test_tick_fired_hit_and_miss_by_lateness(self):
        m, j, obs = _wired()
        j.record("decision", "tick_fired", key=CRON_KEY,
                 cron="default/demo", lateness_s=2.0, deadline_s=30.0)
        j.record("decision", "tick_fired", key=CRON_KEY,
                 cron="default/demo", lateness_s=10.0, deadline_s=30.0)
        j.record("decision", "tick_fired", key=CRON_KEY,
                 cron="default/demo", lateness_s=45.0, deadline_s=30.0)
        slo = obs.report()["deadline_slo"]
        assert slo["hits"] == 2 and slo["misses"] == 1
        assert slo["hit_rate"] == round(2 / 3, 4)
        per = slo["per_cron"]["default/demo"]
        assert per["lateness_p50_s"] == 10.0
        assert per["lateness_p99_s"] == 45.0
        assert m.get("cron_deadline_hits_total") == 2
        assert m.get("cron_deadline_misses_total") == 1

    def test_no_deadline_configured_is_always_a_hit(self):
        _m, j, obs = _wired()
        j.record("decision", "tick_fired", key=CRON_KEY,
                 cron="default/demo", lateness_s=1e6, deadline_s=None)
        slo = obs.report()["deadline_slo"]
        assert slo == dict(slo, hits=1, misses=0, hit_rate=1.0)

    def test_starting_deadline_skip_is_a_miss_policy_skips_are_not(self):
        m, j, obs = _wired()
        j.record("decision", "tick_skipped", key=CRON_KEY,
                 reason="StartingDeadline", cron="default/demo",
                 lateness_s=90.0, deadline_s=30.0)
        j.record("decision", "tick_skipped", key=CRON_KEY,
                 reason="Forbid", cron="default/demo")
        j.record("decision", "tick_skipped", key=CRON_KEY,
                 reason="Replace", cron="default/demo")
        slo = obs.report()["deadline_slo"]
        assert slo["hits"] == 0 and slo["misses"] == 1
        assert m.get("cron_deadline_misses_total") == 1

    def test_fleet_shed_is_a_miss(self):
        m, j, obs = _wired()
        j.record("decision", "tick_shed", key=CRON_KEY,
                 reason="FleetQueueFull", cron="default/demo",
                 lateness_s=1.5, deadline_s=None)
        slo = obs.report()["deadline_slo"]
        assert slo["misses"] == 1
        assert m.get("cron_deadline_misses_total") == 1

    def test_cron_identity_falls_back_to_record_key(self):
        _m, j, obs = _wired()
        j.record("decision", "tick_fired", key=CRON_KEY, lateness_s=0.1)
        assert "default/demo" in obs.report()["deadline_slo"]["per_cron"]

    def test_non_decision_kinds_and_other_events_ignored(self):
        _m, j, obs = _wired()
        j.record("store", "create", key=CRON_KEY, wal_pos=1, rv=1)
        j.record("decision", "job_created", key=CRON_KEY)
        j.record("cluster", "lease_acquired")
        assert obs.records_seen == 0

    def test_slo_table_is_bounded(self):
        _m, j, obs = _wired(max_crons=2)
        for i in range(4):
            j.record("decision", "tick_fired",
                     cron=f"default/cron-{i}", lateness_s=0.0)
        report = obs.report()["deadline_slo"]
        assert len(report["per_cron"]) == 2
        assert obs._slo_dropped == 2


class TestQueueWait:
    def test_distributions_bucketed_by_priority_class(self):
        _m, j, obs = _wired()
        for wait in (0.1, 0.2, 0.3):
            j.record("decision", "fleet_dispatch",
                     key="default/wl", queue_wait_s=wait, priority=50)
        j.record("decision", "fleet_dispatch",
                 key="default/wl", queue_wait_s=4.0, priority=-50)
        j.record("decision", "fleet_dispatch",
                 key="default/wl", queue_wait_s=1.0)  # no priority → normal
        waits = obs.report()["queue_wait_s"]
        assert set(waits) == {"high", "batch", "normal"}
        assert waits["high"]["count"] == 3
        assert waits["high"]["max_s"] == 0.3
        assert waits["batch"]["p50_s"] == 4.0
        assert waits["normal"]["count"] == 1

    def test_garbage_wait_and_priority_tolerated(self):
        _m, j, obs = _wired()
        j.record("decision", "fleet_dispatch",
                 key="default/wl", queue_wait_s="soon", priority="urgent")
        j.record("decision", "fleet_dispatch", key="default/wl")
        assert obs.report()["queue_wait_s"].get("normal", {}) \
            .get("count", 0) == 0


class TestUtilization:
    def test_integrated_chip_seconds_on_simulated_fleet(self):
        m = Metrics()
        obs = FleetObservatory(metrics=m)
        fs = FleetScheduler(
            parse_pool("cpu=2"), api=None,
            on_create=lambda wl, slice_type: None, metrics=m,
        )
        obs.attach_fleet(fs)
        obs.sample_fleet(now_mono=0.0)  # baseline: no interval yet
        assert fs.submit(_job("busy-0")).action == "placed"
        obs.sample_fleet(now_mono=10.0)  # 1 of 2 slices busy for 10 s
        assert fs.release("default", "busy-0")
        obs.sample_fleet(now_mono=20.0)  # idle for the next 10 s
        util = obs.report()["utilization"]["cpu"]
        assert util["busy_chip_s"] == 10.0
        assert util["capacity_chip_s"] == 40.0
        assert util["utilization"] == 0.25
        assert m.get('fleet_utilization{slice_type="cpu"}') == 0.0

    def test_sample_without_fleet_is_a_noop(self):
        obs = FleetObservatory()
        obs.sample_fleet(now_mono=1.0)
        assert obs.report()["utilization"] == {}


class TestZeroStoreWrites:
    def test_report_rollup_render_leave_rv_untouched(self, tmp_path):
        # The observatory folds records the store already audited; its
        # whole read/report/rollup surface must add zero store writes.
        m = Metrics()
        api = APIServer()
        j = AuditJournal(metrics=m)
        api.attach_audit(j)
        obs = FleetObservatory(metrics=m, data_dir=str(tmp_path))
        j.attach_observer(obs.on_record)
        api.create(_job("seed-0"))
        api.create(_job("seed-1"))
        j.record("decision", "tick_fired", key=CRON_KEY,
                 cron="default/demo", lateness_s=0.2, deadline_s=30.0)
        rv_before = api._rv
        obs.report()
        obs.rollup(now=123.0)
        obs.render_json()
        assert api._rv == rv_before
        assert obs.report()["deadline_slo"]["hits"] == 1


class TestRollups:
    def test_jsonl_lines_counter_and_hooks(self, tmp_path):
        m, j, obs = _wired(data_dir=str(tmp_path))
        j.record("decision", "tick_fired",
                 cron="default/demo", lateness_s=0.0)
        fired = []
        obs.add_rollup_hook(lambda: fired.append(1))
        obs.add_rollup_hook(lambda: 1 / 0)  # broken hook is swallowed
        path = obs.rollup(now=1000.0)
        assert path == str(tmp_path / "observatory.jsonl")
        assert obs.rollup(now=2000.0) == path
        lines = [json.loads(ln) for ln in
                 open(path, encoding="utf-8").read().splitlines()]
        assert [ln["ts"] for ln in lines] == [1000.0, 2000.0]
        assert lines[-1]["deadline_slo"]["hits"] == 1
        assert lines[-1]["rollups_total"] == 1  # snapshot before bump
        assert fired == [1, 1]
        assert obs.rollups_total == 2
        assert m.get("observatory_rollups_total") == 2

    def test_no_data_dir_still_counts(self):
        m, _j, obs = _wired()
        assert obs.rollup() is None
        assert obs.rollups_total == 1
        assert m.get("observatory_rollups_total") == 1


class TestRenderJson:
    def test_body_includes_fleet_books_when_attached(self):
        m, j, obs = _wired()
        fs = FleetScheduler(
            parse_pool("cpu=2"), api=None,
            on_create=lambda wl, slice_type: None, metrics=m,
        )
        obs.attach_fleet(fs)
        j.record("decision", "tick_fired",
                 cron="default/demo", lateness_s=0.0)
        body = json.loads(obs.render_json())
        assert body["observatory"]["deadline_slo"]["hits"] == 1
        assert body["fleet"]["policy"] == "hetero"
        assert body["pool"]["cpu"] == {"count": 2, "chips": 1}
        assert "throughput_matrix" in body

    def test_body_without_fleet_is_observatory_only(self):
        _m, _j, obs = _wired()
        body = json.loads(obs.render_json())
        assert set(body) == {"observatory"}


class TestMatrixSidecar:
    def test_save_load_round_trip(self, tmp_path):
        path = str(tmp_path / "fleet_matrix.json")
        matrix = ThroughputMatrix(seed={("w1", "v5e-16"): 10.0})
        matrix.observe("w2", "cpu", 4.0)
        matrix.save(path)
        seed = ThroughputMatrix.load_seed(path)
        assert seed == {("w1", "v5e-16"): 10.0, ("w2", "cpu"): 4.0}
        reborn = ThroughputMatrix(seed=seed)
        assert reborn.snapshot() == matrix.snapshot()

    def test_load_missing_or_corrupt_starts_cold(self, tmp_path):
        assert ThroughputMatrix.load_seed(
            str(tmp_path / "nope.json")
        ) is None
        bad = tmp_path / "bad.json"
        bad.write_text("{not json")
        assert ThroughputMatrix.load_seed(str(bad)) is None
        empty = tmp_path / "empty.json"
        empty.write_text(json.dumps({"alpha": 0.25, "rates": {}}))
        assert ThroughputMatrix.load_seed(str(empty)) is None
