"""Durability layer: WAL + snapshot persistence, crash recovery,
kill-points, and restart catch-up semantics.

Covers the contracts the chaos soak relies on:

- snapshot + WAL-tail replay reconstructs the store exactly (rv and
  counter restoration included), property-style over random verb
  sequences;
- a torn final record is dropped and the file repaired, and recovery is
  idempotent (invariant I6's "pure function of the bytes");
- each seeded kill-point has its documented durability outcome
  (before_append loses the record, after_append orphans it, torn_tail
  truncates it, mid_snapshot leaves an orphaned tmp the next boot
  removes);
- restart catch-up re-fires a missed tick, and
  ``startingDeadlineSeconds`` caps how stale a tick may be and still
  fire after downtime.
"""

import json
import random
import unittest
import tempfile
import os
import shutil
from datetime import timedelta

from cron_operator_tpu.runtime.kube import APIServer
from cron_operator_tpu.runtime.persistence import (
    Persistence,
    SimulatedCrash,
    SNAPSHOT_NAME,
    SNAPSHOT_TMP_NAME,
    WAL_NAME,
)
from cron_operator_tpu.runtime.faults import KILL_POINTS, KillSwitch
from cron_operator_tpu.runtime.manager import Metrics
from cron_operator_tpu.utils.clock import FakeClock

WORKLOAD_API_VERSION = "kubeflow.org/v1"
WORKLOAD_KIND = "JAXJob"


def _obj(name: str, ns: str = "default", kind: str = WORKLOAD_KIND) -> dict:
    return {
        "apiVersion": WORKLOAD_API_VERSION,
        "kind": kind,
        "metadata": {"name": name, "namespace": ns},
        "spec": {"replicaSpecs": {"Worker": {"replicas": 1}}},
    }


def _canonical(objects, rv) -> str:
    return json.dumps(
        {"rv": int(rv), "objects": sorted(
            (dict(o) for o in objects),
            key=lambda o: json.dumps(o, sort_keys=True, default=str),
        )},
        sort_keys=True, default=str,
    )


def _store_canonical(store) -> str:
    return _canonical(store.all_objects(), getattr(store, "_rv"))


class _TmpDirTest(unittest.TestCase):
    def setUp(self):
        self.dir = tempfile.mkdtemp(prefix="persistence-test-")
        self.addCleanup(shutil.rmtree, self.dir, ignore_errors=True)


class TestSnapshotWalEquivalence(_TmpDirTest):
    def _random_soak(self, seed: int, ops: int, fsync_every: int,
                     snapshot_every: int) -> APIServer:
        """Drive a random verb sequence through a persisted store."""
        rng = random.Random(seed)
        store = APIServer(clock=FakeClock())
        pers = Persistence(self.dir, fsync_every=fsync_every,
                           snapshot_every=snapshot_every)
        pers.start(store)
        live: list = []
        for i in range(ops):
            verb = rng.choice(("create", "create", "update",
                               "patch_status", "delete"))
            if verb == "create" or not live:
                name = f"w-{seed}-{i}"
                store.create(_obj(name))
                live.append(name)
            elif verb == "update":
                name = rng.choice(live)
                cur = dict(store.get(WORKLOAD_API_VERSION, WORKLOAD_KIND,
                                     "default", name))
                cur["spec"] = dict(cur["spec"])
                cur["spec"]["round"] = i
                store.update(cur)
            elif verb == "patch_status":
                name = rng.choice(live)
                store.patch_status(
                    WORKLOAD_API_VERSION, WORKLOAD_KIND, "default", name,
                    {"phase": f"step-{i}"},
                )
            else:
                name = live.pop(rng.randrange(len(live)))
                store.delete(WORKLOAD_API_VERSION, WORKLOAD_KIND,
                             "default", name)
        # One post-loop create so the WAL tail is non-empty even when the
        # final random op happened to land exactly on a rotation.
        store.create(_obj(f"w-{seed}-final"))
        pers.flush()
        pers.close()
        return store

    def test_replay_reconstructs_store_exactly(self):
        # Property-style over three seeds: random create/update/patch/
        # delete sequences, small fsync batches, rotations mid-sequence.
        for seed in (0, 1, 2):
            with self.subTest(seed=seed):
                sub = os.path.join(self.dir, str(seed))
                os.makedirs(sub)
                old_dir, self.dir = self.dir, sub
                try:
                    store = self._random_soak(
                        seed, ops=120, fsync_every=7, snapshot_every=40
                    )
                    state = Persistence(sub).recover()
                    self.assertEqual(
                        _store_canonical(store),
                        _canonical(state.objects, state.rv),
                    )
                    # Rotation happened mid-sequence, so the final state
                    # genuinely exercises snapshot + WAL-tail merge.
                    self.assertTrue(state.had_snapshot)
                    self.assertGreater(state.wal_records_replayed, 0)
                finally:
                    self.dir = old_dir

    def test_counters_restored_across_restart(self):
        store = APIServer(clock=FakeClock())
        pers = Persistence(self.dir)
        pers.start(store)
        store.create(_obj("a"))
        store.create(_obj("b"))
        rv_before = int(getattr(store, "_rv"))
        uids = {o["metadata"]["uid"] for o in store.all_objects()}
        pers.close()

        store2 = APIServer(clock=FakeClock())
        state = Persistence(self.dir).start(store2)
        self.assertEqual(int(getattr(store2, "_rv")), rv_before)
        self.assertEqual(state.rv, rv_before)
        created = store2.create(_obj("c"))
        # rv strictly advances past everything ever committed, uid
        # minting never collides with recovered objects, generation
        # restarts per-object as usual.
        self.assertGreater(
            int(created["metadata"]["resourceVersion"]), rv_before
        )
        self.assertNotIn(created["metadata"]["uid"], uids)

    def test_noop_patch_writes_no_wal_records(self):
        store = APIServer(clock=FakeClock())
        pers = Persistence(self.dir)
        pers.start(store)
        store.create(_obj("a"))
        store.patch_status(WORKLOAD_API_VERSION, WORKLOAD_KIND, "default",
                           "a", {"phase": "Running"})
        before = pers.stats()["records_appended"]
        # Semantic no-op: the write path elides the commit entirely, so
        # the WAL sees nothing — steady-state sweeps are persistence-free.
        store.patch_status(WORKLOAD_API_VERSION, WORKLOAD_KIND, "default",
                           "a", {"phase": "Running"})
        self.assertEqual(pers.stats()["records_appended"], before)
        pers.close()

    def test_boot_compaction_writes_snapshot(self):
        store = APIServer(clock=FakeClock())
        pers = Persistence(self.dir)
        pers.start(store)
        store.create(_obj("a"))
        pers.flush()
        pers.close()
        store2 = APIServer(clock=FakeClock())
        Persistence(self.dir).start(store2)
        # Boot compacted: a third recovery sees the snapshot and no
        # pre-snapshot WAL tail to replay.
        state = Persistence(self.dir).recover()
        self.assertTrue(state.had_snapshot)
        self.assertEqual(state.wal_records_replayed, 0)
        self.assertEqual(len(state.objects), 1)


class TestTimeBoundedFlush(_TmpDirTest):
    def test_background_flusher_bounds_loss_in_wall_time(self):
        # A deployment writing fewer than fsync_every records must still
        # be durable within flush_interval_s: kill -9 after the interval
        # loses nothing even though no batch ever filled. (Found by a
        # live CLI drive: trigger + kill -9 lost the whole session.)
        import time

        store = APIServer(clock=FakeClock())
        pers = Persistence(self.dir, fsync_every=64, flush_interval_s=0.05)
        pers.start(store)
        store.create(_obj("w-0"))
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            with open(os.path.join(self.dir, WAL_NAME), "rb") as f:
                if f.read():
                    break
            time.sleep(0.02)
        pers.kill()  # drops any still-buffered suffix, like kill -9
        state = Persistence(self.dir).recover()
        self.assertEqual(
            {o["metadata"]["name"] for o in state.objects}, {"w-0"}
        )

    def test_interval_zero_disables_the_flusher(self):
        store = APIServer(clock=FakeClock())
        pers = Persistence(self.dir, fsync_every=64, flush_interval_s=0)
        pers.start(store)
        store.create(_obj("w-0"))
        self.assertIsNone(pers._flusher)
        pers.kill()  # nothing was flushed — the record is gone
        state = Persistence(self.dir).recover()
        self.assertEqual(state.objects, [])


class TestTornTail(_TmpDirTest):
    def test_torn_tail_dropped_and_repaired(self):
        store = APIServer(clock=FakeClock())
        pers = Persistence(self.dir, fsync_every=1)
        pers.start(store)
        for i in range(3):
            store.create(_obj(f"w-{i}"))
        pers.close()
        wal = os.path.join(self.dir, WAL_NAME)
        with open(wal, "ab") as f:
            f.write(b'{"op":"put","rv":999,"obj":{"tor')  # torn mid-line
        state = Persistence(self.dir).recover()
        self.assertEqual(state.torn_records_dropped, 1)
        self.assertEqual(len(state.objects), 3)
        # The repair truncated the file: recovery is now idempotent and
        # clean (I6: recover twice == recover once).
        again = Persistence(self.dir).recover()
        self.assertEqual(again.torn_records_dropped, 0)
        self.assertEqual(
            _canonical(state.objects, state.rv),
            _canonical(again.objects, again.rv),
        )

    def test_corrupt_middle_record_truncates_rest(self):
        store = APIServer(clock=FakeClock())
        pers = Persistence(self.dir, fsync_every=1)
        pers.start(store)
        store.create(_obj("w-0"))
        pers.close()
        wal = os.path.join(self.dir, WAL_NAME)
        with open(wal, "ab") as f:
            f.write(b"garbage-not-json\n")
            f.write(b'{"op":"put","rv":1000,"obj":{}}\n')
        state = Persistence(self.dir).recover()
        # Appends are strictly ordered: one bad record invalidates the
        # tail; the (syntactically fine) record after it must NOT apply.
        self.assertEqual(len(state.objects), 1)
        self.assertLess(state.rv, 1000)


class TestChecksums(_TmpDirTest):
    """Per-record CRC32C: stamp/verify round trip, legacy acceptance,
    and corruption-aware recovery (invariant I12: no record that fails
    its CRC is ever applied — the suffix is quarantined with
    forensics)."""

    def test_stamp_verify_round_trip(self):
        from cron_operator_tpu.runtime.persistence import (
            split_crc, stamp_crc, verify_line, wal_crc,
        )
        body = json.dumps({"op": "put", "rv": 7, "obj": {"a": 1},
                           "gen": 2}).encode()
        line = stamp_crc(body)
        self.assertTrue(line.endswith(b"}"))
        ok, expected, actual = verify_line(line)
        self.assertTrue(ok)
        self.assertEqual(expected, actual)
        self.assertEqual(expected, wal_crc(body))
        # the stamp is still valid JSON with the CRC as the last key
        rec = json.loads(line)
        self.assertEqual(rec["c"], wal_crc(body))
        # and split_crc recovers the original body exactly
        stripped, crc = split_crc(line)
        self.assertEqual(stripped, body)
        self.assertEqual(crc, wal_crc(body))

    def test_legacy_record_without_crc_accepted(self):
        from cron_operator_tpu.runtime.persistence import verify_line
        legacy = b'{"op":"put","rv":3,"obj":{"x":1}}'
        ok, expected, actual = verify_line(legacy)
        self.assertTrue(ok)
        self.assertIsNone(expected)
        self.assertIsNone(actual)

    def test_single_flipped_digit_detected(self):
        from cron_operator_tpu.runtime.persistence import (
            stamp_crc, verify_line,
        )
        body = b'{"op":"put","rv":1234,"obj":{"n":567}}'
        line = bytearray(stamp_crc(body))
        i = line.index(b"567")
        line[i] = line[i] ^ 0x01  # 5 -> 4: still valid JSON
        ok, expected, actual = verify_line(bytes(line))
        self.assertFalse(ok)
        self.assertNotEqual(expected, actual)

    def test_midfile_corruption_quarantined_with_forensics(self):
        from cron_operator_tpu.runtime.persistence import QUARANTINE_DIR
        store = APIServer(clock=FakeClock())
        pers = Persistence(self.dir, fsync_every=1)
        pers.start(store)
        for i in range(6):
            store.create(_obj(f"w-{i}"))
        pers.close()
        wal = os.path.join(self.dir, WAL_NAME)
        with open(wal, "rb") as f:
            lines = f.read().splitlines(keepends=True)
        # flip one digit inside record 3's payload (a silent bit flip:
        # the line still parses as JSON — only the CRC catches it)
        victim = bytearray(lines[3])
        i = victim.index(b'"rv":') + 5
        victim[i] = victim[i] ^ 0x01
        lines[3] = bytes(victim)
        with open(wal, "wb") as f:
            f.write(b"".join(lines))
        state = Persistence(self.dir).recover()
        self.assertEqual(state.integrity["verdict"], "quarantined")
        self.assertGreaterEqual(state.crc_failures, 1)
        # replay stopped at the last verifiable prefix: records 0-2
        self.assertEqual(
            sorted(o["metadata"]["name"] for o in state.objects),
            ["w-0", "w-1", "w-2"],
        )
        # the suffix (records 3-5) was quarantined, not destroyed
        self.assertEqual(state.quarantined_records, 3)
        qdir = os.path.join(self.dir, QUARANTINE_DIR)
        bins = [p for p in os.listdir(qdir) if p.endswith(".bin")]
        metas = [p for p in os.listdir(qdir) if p.endswith(".json")]
        self.assertEqual(len(bins), 1)
        self.assertEqual(len(metas), 1)
        with open(os.path.join(qdir, metas[0])) as f:
            forensics = json.load(f)
        self.assertEqual(forensics["reason"].split()[0], "crc_mismatch")
        self.assertEqual(forensics["records"], 3)
        self.assertIn("region_crc", forensics)
        # I6 still holds: the repair truncated the segment, so a second
        # recovery is clean and identical
        again = Persistence(self.dir).recover()
        self.assertEqual(again.quarantined_records, 0)
        self.assertEqual(
            _canonical(state.objects, state.rv),
            _canonical(again.objects, again.rv),
        )

    def test_without_checksums_corruption_applies_silently(self):
        """The counter-proof shape: checksums off, the same bit flip is
        parse-valid JSON and recovery APPLIES the corrupt record."""
        store = APIServer(clock=FakeClock())
        pers = Persistence(self.dir, fsync_every=1, checksums=False)
        pers.start(store)
        for i in range(4):
            store.create(_obj(f"w-{i}"))
        pers.close()
        wal = os.path.join(self.dir, WAL_NAME)
        with open(wal, "rb") as f:
            lines = f.read().splitlines(keepends=True)
        victim = bytearray(lines[2])
        i = victim.index(b'"replicas":') + len(b'"replicas":')
        while not chr(victim[i]).isdigit():
            i += 1
        victim[i] = victim[i] ^ 0x01  # replicas 1 -> 0, parse-valid
        lines[2] = bytes(victim)
        with open(wal, "wb") as f:
            f.write(b"".join(lines))
        state = Persistence(self.dir, checksums=False).recover()
        self.assertEqual(state.quarantined_records, 0)
        self.assertEqual(len(state.objects), 4)  # all applied...
        corrupted = [
            o for o in state.objects
            if o["spec"]["replicaSpecs"]["Worker"]["replicas"] != 1
        ]
        self.assertEqual(len(corrupted), 1)  # ...including the lie

    def test_recovery_emits_verified_verdict(self):
        store = APIServer(clock=FakeClock())
        pers = Persistence(self.dir, fsync_every=1)
        pers.start(store)
        for i in range(3):
            store.create(_obj(f"w-{i}"))
        pers.close()
        state = Persistence(self.dir).recover()
        self.assertEqual(state.integrity["verdict"], "verified")
        self.assertGreaterEqual(state.integrity["records_verified"], 3)
        self.assertEqual(state.integrity["records_unverified"], 0)
        self.assertTrue(state.snapshot_digest_verified)


class TestSnapshotDigest(_TmpDirTest):
    def test_corrupt_primary_falls_back_to_previous_snapshot(self):
        from cron_operator_tpu.runtime.persistence import (
            SNAPSHOT_PREV_NAME,
        )
        store = APIServer(clock=FakeClock())
        # snapshot_every=3: two rotations leave snapshot.json AND
        # snapshot.json.1 plus both WAL segments on disk
        pers = Persistence(self.dir, fsync_every=1, snapshot_every=3)
        pers.start(store)
        for i in range(8):
            store.create(_obj(f"w-{i}"))
        pers.close()
        self.assertTrue(os.path.exists(
            os.path.join(self.dir, SNAPSHOT_PREV_NAME)))
        reference = _store_canonical(store)
        # corrupt the PRIMARY snapshot's payload (digest now mismatches)
        snap = os.path.join(self.dir, SNAPSHOT_NAME)
        with open(snap, "rb") as f:
            data = bytearray(f.read())
        i = data.index(b'"rv"') + 7
        data[i] = data[i] ^ 0x01
        with open(snap, "wb") as f:
            f.write(bytes(data))
        state = Persistence(self.dir).recover()
        self.assertTrue(state.snapshot_fallback)
        self.assertEqual(state.integrity["verdict"], "snapshot_fallback")
        # previous snapshot + longer WAL replay reconstructs everything
        store2 = APIServer(clock=FakeClock())
        store2.restore_state(state.objects, state.rv)
        self.assertEqual(_store_canonical(store2), reference)

    def test_legacy_trailerless_snapshot_still_loads(self):
        store = APIServer(clock=FakeClock())
        pers = Persistence(self.dir, fsync_every=1)
        pers.start(store)
        store.create(_obj("w-0"))
        pers.close()
        # strip the digest trailer: pre-CRC format (one payload line)
        snap = os.path.join(self.dir, SNAPSHOT_NAME)
        with open(snap, "rb") as f:
            payload = f.read().split(b"\n", 1)[0]
        with open(snap, "wb") as f:
            f.write(payload + b"\n")
        state = Persistence(self.dir).recover()
        self.assertTrue(state.had_snapshot)
        self.assertFalse(state.snapshot_digest_verified)
        self.assertFalse(state.snapshot_fallback)
        self.assertEqual(len(state.objects), 1)


class TestDegradedMode(_TmpDirTest):
    """Pinned disk-error semantics: EIO/ENOSPC on the write path fails
    the write BEFORE commit (fail-closed), trips read-only degraded
    mode, and auto-recovers when a probe append succeeds."""

    def _open(self, **kw):
        from cron_operator_tpu.runtime.faults import DiskFaultInjector
        from cron_operator_tpu.runtime.persistence import (
            StorageDegradedError,
        )
        store = APIServer(clock=FakeClock())
        inj = DiskFaultInjector(seed=11)
        # probe interval pushed out so the inline auto-heal probe never
        # races the assertions; tests drive probe() explicitly
        pers = Persistence(self.dir, fsync_every=1, disk_faults=inj,
                           degraded_probe_interval_s=60.0)
        pers.start(store)
        return store, pers, inj, StorageDegradedError

    def test_eio_append_fails_before_commit(self):
        store, pers, inj, StorageDegradedError = self._open()
        import errno
        store.create(_obj("healthy"))
        inj.arm_errno("append", errno.EIO)
        with self.assertRaises(StorageDegradedError):
            store.create(_obj("doomed"))
        # fail-CLOSED: the refused write exists nowhere — not in
        # memory, not on disk
        self.assertIsNone(store.get_frozen(
            WORKLOAD_API_VERSION, WORKLOAD_KIND, "default", "doomed"))
        self.assertTrue(pers.degraded)
        self.assertEqual(pers.stats()["degraded"], 1)
        # reads keep serving from memory
        self.assertIsNotNone(store.get_frozen(
            WORKLOAD_API_VERSION, WORKLOAD_KIND, "default", "healthy"))
        # while degraded, further writes refuse without touching disk
        with self.assertRaises(StorageDegradedError):
            store.create(_obj("also-doomed"))
        self.assertGreaterEqual(pers.stats()["degraded_refused"], 1)
        pers.close()

    def test_probe_success_auto_recovers(self):
        store, pers, inj, StorageDegradedError = self._open()
        import errno
        inj.arm_errno("append", errno.ENOSPC)
        with self.assertRaises(StorageDegradedError):
            store.create(_obj("doomed"))
        self.assertTrue(pers.degraded)
        # the injector armed exactly one fault: the next probe append
        # goes through and the layer heals itself
        self.assertTrue(pers.probe())
        self.assertFalse(pers.degraded)
        self.assertEqual(pers.stats()["degraded_exits"], 1)
        # writes flow again, and recovery sees them
        store.create(_obj("after-heal"))
        pers.close()
        state = Persistence(self.dir).recover()
        names = sorted(o["metadata"]["name"] for o in state.objects)
        self.assertIn("after-heal", names)
        self.assertNotIn("doomed", names)

    def test_probe_failure_stays_degraded(self):
        store, pers, inj, StorageDegradedError = self._open()
        import errno
        inj.arm_errno("append", errno.EIO, count=3)
        with self.assertRaises(StorageDegradedError):
            store.create(_obj("doomed"))
        # two more armed faults: the first probe eats one and fails
        self.assertFalse(pers.probe())
        self.assertTrue(pers.degraded)
        self.assertGreaterEqual(pers.probe_failures, 1)
        # third fault eaten; next probe heals
        self.assertFalse(pers.probe())
        self.assertTrue(pers.probe())
        self.assertFalse(pers.degraded)
        pers.close()

    def test_wait_durable_false_while_degraded(self):
        """A record buffered before the device failed: the group-commit
        waiter must fail fast (fail-closed), not spin out its deadline
        pretending the record might still land."""
        import errno
        from cron_operator_tpu.runtime.faults import DiskFaultInjector
        store = APIServer(clock=FakeClock())
        inj = DiskFaultInjector(seed=13)
        # large fsync_every: the create buffers without fsyncing
        pers = Persistence(self.dir, fsync_every=100, disk_faults=inj,
                           degraded_probe_interval_s=60.0)
        pers.start(store)
        store.create(_obj("buffered"))
        inj.arm_errno("fsync", errno.EIO)
        # the waiter leads a group flush, the fsync dies, the layer
        # degrades, and the waiter gets False — not a timeout
        self.assertFalse(pers.wait_durable(timeout=5.0))
        self.assertTrue(pers.degraded)
        pers.close()

    def test_fsync_fault_on_rotation_degrades(self):
        import errno
        from cron_operator_tpu.runtime.faults import DiskFaultInjector
        store = APIServer(clock=FakeClock())
        inj = DiskFaultInjector(seed=12)
        pers = Persistence(self.dir, fsync_every=1, snapshot_every=3,
                           disk_faults=inj,
                           degraded_probe_interval_s=0.0)
        pers.start(store)
        store.create(_obj("w-0"))
        store.create(_obj("w-1"))
        inj.arm_errno("rename", errno.EIO)
        # third create crosses snapshot_every: the rotation's rename
        # fails; the write itself was already durable, the layer
        # degrades instead of crashing
        store.create(_obj("w-2"))
        self.assertTrue(pers.degraded)
        self.assertTrue(pers.probe())
        pers.close()
        # no torn state: recovery converges on all three objects
        state = Persistence(self.dir).recover()
        self.assertEqual(len(state.objects), 3)


class TestScrubber(_TmpDirTest):
    def _sealed_segment(self):
        """Build a dir with a sealed wal.jsonl.1 + both snapshots."""
        store = APIServer(clock=FakeClock())
        pers = Persistence(self.dir, fsync_every=1, snapshot_every=3)
        pers.start(store)
        for i in range(8):
            store.create(_obj(f"w-{i}"))
        return store, pers

    def test_clean_pass_verifies_cold_bytes(self):
        from cron_operator_tpu.runtime.persistence import Scrubber
        store, pers = self._sealed_segment()
        m = Metrics()
        scrub = Scrubber(pers, interval_s=0.0)
        scrub.instrument(m)
        summary = scrub.scrub_once()
        self.assertEqual(summary["corruptions_found"], 0)
        self.assertGreater(summary["records_verified"], 0)
        self.assertEqual(summary["findings"], [])
        self.assertEqual(m.get("scrub_passes_total"), 1.0)
        pers.close()

    def test_detects_latent_corruption_in_sealed_segment(self):
        from cron_operator_tpu.runtime.persistence import (
            Scrubber, WAL_PREV_NAME,
        )
        store, pers = self._sealed_segment()
        prev = os.path.join(self.dir, WAL_PREV_NAME)
        self.assertTrue(os.path.exists(prev))
        with open(prev, "rb") as f:
            data = bytearray(f.read())
        i = data.index(b'"rv":') + 5
        data[i] = data[i] ^ 0x01
        with open(prev, "wb") as f:
            f.write(bytes(data))
        m = Metrics()
        scrub = Scrubber(pers, interval_s=0.0)
        scrub.instrument(m)
        summary = scrub.scrub_once()
        self.assertEqual(summary["corruptions_found"], 1)
        self.assertEqual(summary["findings"][0]["kind"],
                         "wal_crc_mismatch")
        self.assertEqual(
            m.get('wal_crc_failures_total{site="scrub"}'), 1.0)
        pers.close()

    def test_detects_snapshot_digest_rot(self):
        from cron_operator_tpu.runtime.persistence import Scrubber
        store, pers = self._sealed_segment()
        snap = os.path.join(self.dir, SNAPSHOT_NAME)
        with open(snap, "rb") as f:
            data = bytearray(f.read())
        i = data.index(b'"objects"') + 3
        data[i] = data[i] ^ 0x20
        with open(snap, "wb") as f:
            f.write(bytes(data))
        scrub = Scrubber(pers, interval_s=0.0)
        summary = scrub.scrub_once()
        kinds = [f["kind"] for f in summary["findings"]]
        self.assertIn("snapshot_digest_mismatch", kinds)
        pers.close()

    def test_detects_replica_divergence_only_at_equal_rv(self):
        from cron_operator_tpu.runtime.persistence import Scrubber
        store, pers = self._sealed_segment()
        scrub = Scrubber(pers, interval_s=0.0)
        scrub.leader_probe = lambda: (42, "digest-A")
        # lagging follower: different rv — lag, not damage
        scrub.follower_probes["lagging"] = lambda: (40, "digest-old")
        summary = scrub.scrub_once()
        self.assertEqual(summary["corruptions_found"], 0)
        # diverged follower: same rv, different digest — damage
        scrub.follower_probes["diverged"] = lambda: (42, "digest-B")
        summary = scrub.scrub_once()
        self.assertEqual(summary["corruptions_found"], 1)
        self.assertEqual(summary["findings"][0]["kind"],
                         "replica_divergence")
        pers.close()


class TestKillPoints(_TmpDirTest):
    def _crash_run(self, seed: int, data_dir: str):
        """Create objects until the seeded kill fires; returns
        (store, pers, names_attempted, crashed_name)."""
        store = APIServer(clock=FakeClock())
        # fsync_every=1 keeps the pre-kill prefix durable, so each test
        # isolates its kill-point's OWN record semantics (fsync batching
        # and suffix loss have their own coverage in the chaos soak).
        pers = Persistence(data_dir, fsync_every=1,
                           kill_switch=KillSwitch(seed, 0))
        pers.start(store)
        crashed = None
        names = []
        for i in range(64):
            name = f"w-{i}"
            names.append(name)
            try:
                store.create(_obj(name))
            except SimulatedCrash:
                crashed = name
                break
        return store, pers, names, crashed

    def test_kill_switch_is_deterministic(self):
        for seed in range(8):
            a, b = KillSwitch(seed, 0), KillSwitch(seed, 0)
            self.assertEqual(a.describe(), b.describe())
            self.assertIn(a.point, KILL_POINTS)

    def test_same_seed_same_crash_same_recovery(self):
        # Seeds chosen to pin each kill-point (see KillSwitch PRF):
        # 5=before_append, 12=after_append, 0=torn_tail, 3=mid_snapshot,
        # 16=mid_rotate_demote, 1=mid_rotate_wal.
        for seed in (5, 12, 0, 3, 16, 1):
            with self.subTest(seed=seed):
                d1 = os.path.join(self.dir, f"a{seed}")
                d2 = os.path.join(self.dir, f"b{seed}")
                s1 = self._crash_run(seed, d1)
                s2 = self._crash_run(seed, d2)
                self.assertTrue(s1[1].dead)
                self.assertEqual(s1[3], s2[3])  # same create crashed
                r1 = Persistence(d1).recover()
                r2 = Persistence(d2).recover()

                def scrub(objects):
                    # uids are minted from os randomness (correctly NOT
                    # seeded); everything else must match bit-for-bit.
                    out = []
                    for o in objects:
                        o = json.loads(json.dumps(o, default=str))
                        o.get("metadata", {}).pop("uid", None)
                        out.append(o)
                    return out

                self.assertEqual(
                    _canonical(scrub(r1.objects), r1.rv),
                    _canonical(scrub(r2.objects), r2.rv),
                )

    def test_before_append_loses_record_and_commit(self):
        store, pers, names, crashed = self._crash_run(5, self.dir)
        self.assertEqual(pers.kill_switch.point, "before_append")
        self.assertIsNotNone(crashed)
        state = Persistence(self.dir).recover()
        recovered = {o["metadata"]["name"] for o in state.objects}
        in_store = {o["metadata"]["name"] for o in store.all_objects()}
        # Lost entirely: neither durable nor committed — a clean failure
        # the caller saw an exception for.
        self.assertNotIn(crashed, recovered)
        self.assertNotIn(crashed, in_store)
        self.assertEqual(recovered, in_store)

    def test_after_append_orphans_the_record(self):
        store, pers, names, crashed = self._crash_run(12, self.dir)
        self.assertEqual(pers.kill_switch.point, "after_append")
        state = Persistence(self.dir).recover()
        recovered = {o["metadata"]["name"] for o in state.objects}
        in_store = {o["metadata"]["name"] for o in store.all_objects()}
        # The "fsynced but the 200 was lost" window: durable on disk,
        # never committed in memory — recovery resurrects an object the
        # submitter believes failed (the chaos soak's "orphan").
        self.assertIn(crashed, recovered)
        self.assertNotIn(crashed, in_store)

    def test_after_append_on_delete_is_a_phantom_delete(self):
        # The mirror image of the orphan: after_append fires on a DEL
        # record, so the delete is durable but the in-memory evict (and
        # its DELETED watch event) never happened. Recovery must honor
        # the disk — and surface the key via wal_deleted_keys so
        # restart-aware observers can reconcile the missing event.
        store = APIServer(clock=FakeClock())
        pers = Persistence(self.dir, fsync_every=1,
                           kill_switch=KillSwitch(357, 0))  # after_append@3
        pers.start(store)
        store.create(_obj("w-0"))
        store.create(_obj("w-1"))
        with self.assertRaises(SimulatedCrash):
            store.delete(WORKLOAD_API_VERSION, WORKLOAD_KIND,
                         "default", "w-1")
        self.assertEqual(pers.kill_switch.point, "after_append")
        in_store = {o["metadata"]["name"] for o in store.all_objects()}
        self.assertIn("w-1", in_store)  # evict aborted — memory kept it
        state = Persistence(self.dir).recover()
        recovered = {o["metadata"]["name"] for o in state.objects}
        self.assertEqual(recovered, {"w-0"})  # disk's verdict: deleted
        self.assertIn(
            (WORKLOAD_API_VERSION, WORKLOAD_KIND, "default", "w-1"),
            [tuple(k) for k in state.wal_deleted_keys],
        )

    def test_torn_tail_truncates_the_record(self):
        store, pers, names, crashed = self._crash_run(0, self.dir)
        self.assertEqual(pers.kill_switch.point, "torn_tail")
        state = Persistence(self.dir).recover()
        recovered = {o["metadata"]["name"] for o in state.objects}
        self.assertEqual(state.torn_records_dropped, 1)
        self.assertNotIn(crashed, recovered)
        # Everything before the torn record was force-flushed.
        self.assertEqual(
            recovered, {o["metadata"]["name"] for o in store.all_objects()}
        )

    def test_mid_snapshot_leaves_orphan_tmp_commit_survives(self):
        store, pers, names, crashed = self._crash_run(3, self.dir)
        self.assertEqual(pers.kill_switch.point, "mid_snapshot")
        # The TRIGGERING commit succeeded (death happened in background
        # compaction, after the rename's tmp was written) — it is the
        # NEXT create that observes the dead layer and crashes.
        self.assertTrue(pers.dead)
        trigger = names[-2]
        self.assertIn(
            trigger,
            {o["metadata"]["name"] for o in store.all_objects()},
        )
        self.assertTrue(
            os.path.exists(os.path.join(self.dir, SNAPSHOT_TMP_NAME))
        )
        state = Persistence(self.dir).recover()
        # Orphaned tmp removed; WAL (flushed before the snapshot was
        # attempted) covers every commit including the triggering one.
        self.assertFalse(
            os.path.exists(os.path.join(self.dir, SNAPSHOT_TMP_NAME))
        )
        self.assertEqual(
            {o["metadata"]["name"] for o in state.objects},
            {o["metadata"]["name"] for o in store.all_objects()},
        )

    def test_mid_rotate_demote_recovers_from_previous_snapshot(self):
        # Death AFTER the old snapshot was demoted to snapshot.json.1
        # but BEFORE the new one was installed: no primary snapshot on
        # disk at all. Recovery must chain snapshot.json.1 + both WAL
        # segments to the exact committed state.
        store, pers, names, crashed = self._crash_run(16, self.dir)
        self.assertEqual(pers.kill_switch.point, "mid_rotate_demote")
        self.assertTrue(pers.dead)
        self.assertFalse(os.path.exists(os.path.join(self.dir, SNAPSHOT_NAME)))
        state = Persistence(self.dir).recover()
        self.assertEqual(
            {o["metadata"]["name"] for o in state.objects},
            {o["metadata"]["name"] for o in store.all_objects()},
        )

    def test_mid_rotate_wal_skips_stale_records(self):
        # Death AFTER the new snapshot was installed but BEFORE the WAL
        # segment it compacted was rotated aside: every record in the
        # live WAL is <= the snapshot rv and must be rv-skipped.
        store, pers, names, crashed = self._crash_run(1, self.dir)
        self.assertEqual(pers.kill_switch.point, "mid_rotate_wal")
        self.assertTrue(pers.dead)
        state = Persistence(self.dir).recover()
        self.assertGreater(state.wal_records_skipped, 0)
        self.assertEqual(
            {o["metadata"]["name"] for o in state.objects},
            {o["metadata"]["name"] for o in store.all_objects()},
        )

    def test_every_rotate_interleaving_converges(self):
        # The rotate-phase kill-point table, end to end: for each phase,
        # crash there, recover, and confirm the recovered dir (a) equals
        # the committed store and (b) re-recovers identically (I6).
        for seed, point in ((3, "mid_snapshot"), (16, "mid_rotate_demote"),
                            (1, "mid_rotate_wal")):
            with self.subTest(point=point):
                d = os.path.join(self.dir, point)
                store, pers, names, crashed = self._crash_run(seed, d)
                self.assertEqual(pers.kill_switch.point, point)
                s1 = Persistence(d).recover()
                s2 = Persistence(d).recover()
                self.assertEqual(
                    _canonical(s1.objects, s1.rv),
                    _canonical(s2.objects, s2.rv),
                )
                self.assertEqual(
                    {o["metadata"]["name"] for o in s1.objects},
                    {o["metadata"]["name"] for o in store.all_objects()},
                )


class TestShipSinkBackpressure(_TmpDirTest):
    """The async bounded ship queue (Persistence._ship): a wedged
    follower sink must never block the leader's write path — the queue
    drops whole, counts a stall, and the sink resyncs from durable
    state once it unwedges."""

    def test_wedged_sink_drop_then_resync(self):
        import threading
        import time as _time

        from cron_operator_tpu.runtime.shard import (
            FollowerReplica,
            canonical_state,
        )
        from cron_operator_tpu.utils.clock import RealClock

        store = APIServer(clock=FakeClock())
        metrics = Metrics()
        pers = Persistence(self.dir, fsync_every=1)
        pers.instrument(metrics)
        pers.start(store)
        self.addCleanup(pers.close)

        replica = FollowerReplica(RealClock(), name="wedged")
        gate = threading.Event()

        def wedged_apply(data: bytes) -> None:
            gate.wait()  # deliberately wedged until the test opens it
            replica.apply_bytes(data)

        sink = pers.attach_sink(
            wedged_apply, resync=replica.resync, name="wedged",
            max_buffered_bytes=512,  # tiny: the wedge must trip fast
        )

        t0 = _time.monotonic()
        for i in range(100):
            store.create(_obj(f"w-{i}"))
        elapsed = _time.monotonic() - t0
        pers.flush()

        # The whole burst committed without waiting on the wedged sink.
        self.assertEqual(len(store), 100)
        self.assertLess(elapsed, 5.0)
        # The bounded queue overflowed: dropped whole + stall counted
        # (both on the sink and in the metrics registry) + resync armed.
        self.assertGreaterEqual(sink.stalls, 1)
        self.assertGreaterEqual(
            metrics.counters.get("shard_follower_stalls_total", 0), 1)

        # Unwedge: the pending resync re-seeds the replica from durable
        # state; it must converge to exactly the on-disk replay.
        gate.set()
        self.assertTrue(pers.drain_shippers(timeout=10.0))
        replay = Persistence(self.dir).recover()
        self.assertEqual(
            replica.state(),
            canonical_state(replay.objects, replay.rv),
        )
        self.assertGreaterEqual(sink.resyncs, 1)


class TestTornTailOverSocket(_TmpDirTest):
    """Satellite: the torn-tail contract extended to the socket path. A
    WAL record deliberately torn at the kill-point ships to a socket
    follower as-is; the follower must hold it unapplied (line
    buffering) and end byte-identical to an independent on-disk
    replay — never a partial apply."""

    def test_torn_tail_socket_follower_equals_disk_replay(self):
        import time as _time

        from cron_operator_tpu.runtime.shard import (
            FollowerReplica,
            canonical_state,
        )
        from cron_operator_tpu.runtime.transport import (
            ShipFollower,
            WALShipServer,
        )
        from cron_operator_tpu.utils.clock import RealClock

        store = APIServer(clock=FakeClock())
        # Seed 0 pins the torn_tail kill-point (see KillSwitch PRF).
        pers = Persistence(self.dir, fsync_every=1,
                           kill_switch=KillSwitch(0, 0))
        pers.start(store)
        server = WALShipServer(pers)
        self.addCleanup(server.close)
        replica = FollowerReplica(RealClock(), name="torn-socket")
        follower = ShipFollower("127.0.0.1", server.port, replica)
        self.addCleanup(follower.stop)
        self.assertTrue(follower.wait_connected(5.0))

        crashed = None
        for i in range(64):
            try:
                store.create(_obj(f"w-{i}"))
            except SimulatedCrash:
                crashed = f"w-{i}"
                break
        self.assertEqual(pers.kill_switch.point, "torn_tail")
        self.assertIsNotNone(crashed)
        # The kill-point ships the torn fragment itself; deliver every
        # queued byte (the "kernel accepted it" analog), then compare.
        pers.drain_shippers(timeout=10.0)

        replay = Persistence(self.dir).recover()
        self.assertEqual(replay.torn_records_dropped, 1)
        deadline = _time.monotonic() + 10.0
        want = canonical_state(replay.objects, replay.rv)
        # Wait for the follower to consume everything the drain handed to
        # the socket: the intact records (state converges to the replay)
        # AND the trailing fragment (parks in the line buffer — it can
        # arrive after state already matches, so poll for both).
        while _time.monotonic() < deadline and (
                replica.state() != want or len(replica._tail) == 0):
            _time.sleep(0.02)
        # End state ≡ disk replay: the torn record applied NOWHERE.
        self.assertEqual(replica.state(), want)
        names = {o["metadata"]["name"] for o in replica.store.all_objects()}
        self.assertNotIn(crashed, names)
        # The fragment is visibly parked in the line buffer, unapplied.
        self.assertGreater(len(replica._tail), 0)
        pers.close_shippers()


class TestRestartCatchup(_TmpDirTest):
    """Downtime crosses tick boundaries: catch-up fires the missed tick
    unless ``startingDeadlineSeconds`` says it is too stale."""

    def _setup(self, starting_deadline=None):
        from cron_operator_tpu.controller.cron_controller import (
            CronReconciler,
        )

        clock = FakeClock()
        store = APIServer(clock=clock)
        pers = Persistence(self.dir, fsync_every=1)
        pers.start(store)
        spec = {
            "schedule": "*/1 * * * *",
            "concurrencyPolicy": "Allow",
            "historyLimit": 3,
            "template": {"workload": {
                "apiVersion": WORKLOAD_API_VERSION,
                "kind": WORKLOAD_KIND,
                "metadata": {},
                "spec": {"replicaSpecs": {"Worker": {"replicas": 1}}},
            }},
        }
        if starting_deadline is not None:
            spec["startingDeadlineSeconds"] = starting_deadline
        store.create({
            "apiVersion": "apps.kubedl.io/v1alpha1",
            "kind": "Cron",
            "metadata": {"name": "nightly", "namespace": "default"},
            "spec": spec,
        })
        metrics = Metrics()
        rec = CronReconciler(store, metrics=metrics)
        return clock, store, pers, rec, metrics

    def _workload_names(self, store):
        return sorted(
            w["metadata"]["name"] for w in store.list(
                WORKLOAD_API_VERSION, WORKLOAD_KIND, namespace="default"
            )
        )

    def _restart(self, pers, clock):
        from cron_operator_tpu.controller.cron_controller import (
            CronReconciler,
        )

        pers.kill("test-crash")
        store = APIServer(clock=clock)
        metrics = Metrics()
        pers2 = Persistence(self.dir)
        pers2.start(store)
        return store, pers2, CronReconciler(store, metrics=metrics), metrics

    def test_catchup_fires_missed_tick_after_downtime(self):
        clock, store, pers, rec, _ = self._setup()
        clock.advance(timedelta(seconds=60))
        rec.reconcile("default", "nightly")
        before = self._workload_names(store)
        self.assertEqual(len(before), 1)

        store2, pers2, rec2, _ = self._restart(pers, clock)
        # 90 s of downtime: one tick boundary crossed while dead.
        clock.advance(timedelta(seconds=90))
        rec2.reconcile("default", "nightly")
        after = self._workload_names(store2)
        self.assertEqual(len(after), 2)
        self.assertEqual(after[0], before[0])  # recovered, not re-fired

    def test_starting_deadline_skips_stale_tick(self):
        clock, store, pers, rec, _ = self._setup(starting_deadline=20)
        clock.advance(timedelta(seconds=60))
        rec.reconcile("default", "nightly")
        before = self._workload_names(store)

        store2, pers2, rec2, metrics = self._restart(pers, clock)
        # Missed tick is 30 s stale on recovery — past the 20 s deadline.
        clock.advance(timedelta(seconds=90))
        rec2.reconcile("default", "nightly")
        self.assertEqual(self._workload_names(store2), before)
        self.assertEqual(
            metrics.get(
                'cron_ticks_skipped_total{policy="StartingDeadline"}'
            ),
            1.0,
        )
        # Skip did not advance lastScheduleTime: the tick stays visibly
        # missed (and is re-skipped, deduped) until superseded.
        rec2.reconcile("default", "nightly")
        self.assertEqual(
            metrics.get(
                'cron_ticks_skipped_total{policy="StartingDeadline"}'
            ),
            1.0,
        )

    def test_fresh_tick_fires_despite_deadline(self):
        clock, store, pers, rec, _ = self._setup(starting_deadline=20)
        clock.advance(timedelta(seconds=60))
        rec.reconcile("default", "nightly")
        before = self._workload_names(store)

        store2, pers2, rec2, _ = self._restart(pers, clock)
        clock.advance(timedelta(seconds=90))
        rec2.reconcile("default", "nightly")  # stale tick: skipped
        # Ten more seconds brings a NEW tick boundary within deadline.
        clock.advance(timedelta(seconds=40))
        rec2.reconcile("default", "nightly")
        after = self._workload_names(store2)
        self.assertEqual(len(after), len(before) + 1)


if __name__ == "__main__":
    unittest.main()
