"""Concurrency stress tier (SURVEY.md §5 "race detection: none in the
reference; enable in CI — cheap win"). Python has no -race flag, so this
tier hammers the thread-shared structures directly and asserts the
invariants that a data race would break:

- APIServer: concurrent writers + cascading deletes + a deliberately slow
  subscriber; resourceVersions observed by a watcher must be strictly
  increasing (global publish order), no write may fail with anything but
  the expected optimistic-concurrency errors, and flush() must drain.
- WorkQueue: concurrent producers + consumers with rate-limited re-adds;
  every item is eventually processed exactly while queued (no lost or
  duplicated in-flight marks).
- Manager + reconciler: full stack under concurrent Cron churn — no
  reconcile error counter increments and the manager stops cleanly.
"""

import threading
import time

from cron_operator_tpu.api.scheme import GVK_CRON, default_scheme
from cron_operator_tpu.controller import CronReconciler
from cron_operator_tpu.runtime import APIServer, Manager
from cron_operator_tpu.runtime.kube import (
    AlreadyExistsError,
    ConflictError,
    NotFoundError,
)
from cron_operator_tpu.runtime.workqueue import WorkQueue

N_THREADS = 8
OPS_PER_THREAD = 60


def _job(name, ns="default"):
    return {
        "apiVersion": "kubeflow.org/v1",
        "kind": "JAXJob",
        "metadata": {"name": name, "namespace": ns},
        "spec": {"replicaSpecs": {"Worker": {"replicas": 1}}},
    }


class TestAPIServerUnderContention:
    def test_concurrent_crud_keeps_watch_order_and_store_sane(self):
        api = APIServer()
        seen_rv = []
        seen_lock = threading.Lock()

        def watcher(ev):
            time.sleep(0.0005)  # slow subscriber: the old sync fan-out
            with seen_lock:     # would serialize every write behind this
                seen_rv.append(int(ev.object["metadata"]["resourceVersion"]))

        api.add_watcher(watcher)
        errors = []

        def worker(i):
            try:
                for n in range(OPS_PER_THREAD):
                    name = f"w{i}-{n}"
                    api.create(_job(name))
                    api.patch_status(
                        "kubeflow.org/v1", "JAXJob", "default", name,
                        {"conditions": [{"type": "Running",
                                         "status": "True"}]},
                    )
                    if n % 2 == 0:
                        api.delete("kubeflow.org/v1", "JAXJob", "default",
                                   name)
            except (AlreadyExistsError, ConflictError, NotFoundError):
                pass  # legal outcomes under contention
            except Exception as exc:  # noqa: BLE001 — the assertion target
                errors.append(exc)

        threads = [
            threading.Thread(target=worker, args=(i,))
            for i in range(N_THREADS)
        ]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        write_elapsed = time.monotonic() - t0

        assert not errors, errors
        assert api.flush(timeout=60), "dispatcher failed to drain"
        api.close()

        # Publish order is global FIFO: the rv sequence a subscriber sees
        # must be strictly increasing. A race between store mutation and
        # queue append would reorder it.
        assert seen_rv == sorted(seen_rv)
        assert len(seen_rv) == len(set(seen_rv))
        # ~1200 events × 0.5 ms slow subscriber ≈ 0.6 s of delivery that
        # must NOT have serialized the writers.
        n_events = N_THREADS * OPS_PER_THREAD * 2.5
        assert write_elapsed < 0.002 * n_events + 30, (
            f"writers appear serialized behind the subscriber "
            f"({write_elapsed:.1f}s)"
        )
        # Store invariant: exactly the odd-n jobs remain.
        remaining = api.list("kubeflow.org/v1", "JAXJob")
        assert len(remaining) == N_THREADS * OPS_PER_THREAD // 2

    def test_cascade_delete_under_concurrent_child_creation(self):
        api = APIServer()
        owner = api.create(_job("owner"))
        uid = owner["metadata"]["uid"]
        stop = threading.Event()
        created = []

        def spawner():
            i = 0
            while not stop.is_set():
                try:
                    api.create({
                        "apiVersion": "v1", "kind": "Pod",
                        "metadata": {
                            "name": f"child-{i}", "namespace": "default",
                            "ownerReferences": [
                                {"kind": "JAXJob", "uid": uid,
                                 "controller": True}
                            ],
                        },
                    })
                    created.append(i)
                except Exception:
                    break
                i += 1

        t = threading.Thread(target=spawner)
        t.start()
        time.sleep(0.05)
        api.delete("kubeflow.org/v1", "JAXJob", "default", "owner")
        stop.set()
        t.join(timeout=10)
        # The point is liveness: a cascade racing child creation must not
        # deadlock or crash. Stragglers created after the cascade are
        # orphans (kube GC semantics — no owner resurrection).
        assert api.try_get("kubeflow.org/v1", "JAXJob", "default",
                           "owner") is None
        assert created, "spawner never ran"


class TestWorkQueueUnderContention:
    def test_no_lost_items(self):
        q = WorkQueue()
        processed = {}
        lock = threading.Lock()
        n_items = 300

        def producer():
            for i in range(n_items):
                q.add(i % 50)  # heavy dedup pressure

        def consumer():
            while True:
                item = q.get(timeout=0.5)
                if item is None:
                    return
                with lock:
                    processed[item] = processed.get(item, 0) + 1
                q.forget(item)
                q.done(item)

        producers = [threading.Thread(target=producer) for _ in range(4)]
        consumers = [threading.Thread(target=consumer) for _ in range(4)]
        for t in producers + consumers:
            t.start()
        for t in producers:
            t.join(timeout=30)
        time.sleep(0.6)
        q.shut_down()
        for t in consumers:
            t.join(timeout=30)
        # Dedup may coalesce concurrent adds, but every key must have been
        # processed at least once and the queue must end empty.
        assert set(processed) == set(range(50))


class TestFullStackChurn:
    def test_manager_survives_cron_churn(self):
        api = APIServer()
        mgr = Manager(api, max_concurrent_reconciles=8)
        rec = CronReconciler(api, metrics=mgr.metrics)
        mgr.add_controller(
            "cron", rec.reconcile, for_gvk=GVK_CRON,
            owns=default_scheme().workload_kinds(),
        )
        mgr.start()

        def churn(i):
            for n in range(10):
                name = f"c{i}-{n}"
                api.create({
                    "apiVersion": "apps.kubedl.io/v1alpha1", "kind": "Cron",
                    "metadata": {"name": name, "namespace": "default"},
                    "spec": {
                        "schedule": "@every 1s",
                        "template": {"workload": {
                            "apiVersion": "kubeflow.org/v1",
                            "kind": "JAXJob",
                            "spec": {"replicaSpecs": {
                                "Worker": {"replicas": 1}}},
                        }},
                    },
                })
                if n % 2 == 0:
                    api.delete("apps.kubedl.io/v1alpha1", "Cron",
                               "default", name)

        threads = [
            threading.Thread(target=churn, args=(i,)) for i in range(6)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        time.sleep(1.0)
        mgr.stop()
        api.close()
        errs = [
            (k, v) for k, v in mgr.metrics.snapshot().items()
            if k.startswith("controller_runtime_reconcile_errors") and v > 0
        ]
        assert not errs, f"reconcile errors under churn: {errs}"
