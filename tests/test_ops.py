"""Pallas flash-attention kernel tests (interpret mode on CPU) and
attention dispatch."""

import jax
import jax.numpy as jnp
import pytest

from cron_operator_tpu.ops.attention import (
    multi_head_attention,
    reference_attention,
)
from cron_operator_tpu.ops.flash_attention import flash_attention


@pytest.fixture(scope="module")
def cpu0():
    return jax.devices("cpu")[0]


@pytest.fixture(scope="module")
def qkv(cpu0):
    with jax.default_device(cpu0):
        key = jax.random.PRNGKey(7)
        b, s, h, d = 2, 256, 2, 64
        return tuple(
            jax.random.normal(k, (b, s, h, d), jnp.float32)
            for k in jax.random.split(key, 3)
        )


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference(self, qkv, cpu0, causal):
        q, k, v = qkv
        with jax.default_device(cpu0):
            ref = reference_attention(q, k, v, causal=causal)
            out = flash_attention(q, k, v, causal=causal, interpret=True)
        assert jnp.max(jnp.abs(out - ref)) < 2e-5

    def test_small_blocks(self, qkv, cpu0):
        q, k, v = qkv
        with jax.default_device(cpu0):
            ref = reference_attention(q, k, v, causal=True)
            out = flash_attention(
                q, k, v, causal=True, block_q=64, block_k=64, interpret=True
            )
        assert jnp.max(jnp.abs(out - ref)) < 2e-5

    @pytest.mark.parametrize("causal", [False, True])
    def test_gqa_matches_repeated_reference(self, cpu0, causal):
        """Kernel-native GQA (index-mapped K/V specs): forward must match
        dense attention over explicitly repeated K/V heads, and grads
        must match jax.grad through the repeat (dk/dv come back at the
        grouped head count, group-summed in f32)."""
        with jax.default_device(cpu0):
            key = jax.random.PRNGKey(11)
            b, s, h, kv_h, d = 2, 256, 4, 2, 32
            kq, kk, kv_, kd = jax.random.split(key, 4)
            q = jax.random.normal(kq, (b, s, h, d), jnp.float32)
            k = jax.random.normal(kk, (b, s, kv_h, d), jnp.float32)
            v = jax.random.normal(kv_, (b, s, kv_h, d), jnp.float32)

            def rep(x):
                return jnp.repeat(x, h // kv_h, axis=2)

            ref = reference_attention(q, rep(k), rep(v), causal=causal)
            out = flash_attention(q, k, v, causal=causal, interpret=True)
            assert out.shape == (b, s, h, d)
            assert jnp.max(jnp.abs(out - ref)) < 2e-5

            do = jax.random.normal(kd, (b, s, h, d), jnp.float32)

            def flash_loss(q, k, v):
                return jnp.sum(
                    flash_attention(
                        q, k, v, causal=causal, interpret=True
                    ) * do
                )

            def ref_loss(q, k, v):
                return jnp.sum(
                    reference_attention(
                        q, rep(k), rep(v), causal=causal
                    ) * do
                )

            g_flash = jax.grad(flash_loss, argnums=(0, 1, 2))(q, k, v)
            g_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
            for a, r in zip(g_flash, g_ref):
                assert a.shape == r.shape
                assert jnp.max(jnp.abs(a - r)) < 5e-4

    def test_gqa_rejects_bad_head_ratio(self, cpu0):
        with jax.default_device(cpu0):
            q = jnp.ones((1, 128, 4, 8))
            k = jnp.ones((1, 128, 3, 8))
            with pytest.raises(ValueError, match="positive divisor"):
                flash_attention(q, k, k, interpret=True)

    def test_rejects_unaligned_seq(self, cpu0):
        with jax.default_device(cpu0):
            q = jnp.ones((1, 100, 1, 8))
            with pytest.raises(ValueError, match="multiple of block sizes"):
                flash_attention(q, q, q)


class TestFlashUnderSharding:
    """The flash kernel must have explicit placement under a mesh (it is
    shard_map-wrapped over batch/head axes — ADVICE r1); numerics must
    match the dense reference shard-for-shard."""

    def test_flash_sharded_batch_matches_reference(self, qkv):
        from cron_operator_tpu.parallel.mesh import mesh_for_devices

        mesh = mesh_for_devices(jax.devices("cpu"))  # 8-way data axis
        q, k, v = (jnp.concatenate([x] * 4, axis=0) for x in qkv)  # b=8
        ref = reference_attention(q, k, v, causal=True)
        out = multi_head_attention(
            q, k, v, causal=True, impl="flash", mesh=mesh, interpret=True
        )
        assert jnp.max(jnp.abs(out - ref)) < 2e-5

    def test_flash_sharded_heads_over_tensor(self, qkv):
        from cron_operator_tpu.parallel.mesh import mesh_for_devices

        mesh = mesh_for_devices(jax.devices("cpu"), tensor=2)  # data×tensor
        q, k, v = (jnp.concatenate([x] * 2, axis=0) for x in qkv)  # b=4,h=2
        ref = reference_attention(q, k, v)
        out = multi_head_attention(
            q, k, v, impl="flash", mesh=mesh, interpret=True
        )
        assert jnp.max(jnp.abs(out - ref)) < 2e-5

    def test_flash_init_trace_shapes_run_locally(self, qkv):
        # batch-of-1 init traces don't divide the data axes: local kernel.
        from cron_operator_tpu.parallel.mesh import mesh_for_devices

        mesh = mesh_for_devices(jax.devices("cpu"))
        q = jnp.ones((1, 256, 2, 64))
        out = multi_head_attention(
            q, q, q, impl="flash", mesh=mesh, interpret=True
        )
        assert out.shape == q.shape

    def test_long_context_streams(self, cpu0):
        # 2048 tokens with 128-blocks: 16 KV blocks stream through scratch;
        # numerics must still match the dense reference.
        with jax.default_device(cpu0):
            key = jax.random.PRNGKey(3)
            q, k, v = (
                jax.random.normal(kk, (1, 2048, 1, 64), jnp.float32)
                for kk in jax.random.split(key, 3)
            )
            ref = reference_attention(q, k, v, causal=True)
            out = flash_attention(q, k, v, causal=True, interpret=True)
        assert jnp.max(jnp.abs(out - ref)) < 2e-5


class TestFlashGrad:
    """VERDICT r2 #2: the kernel must be differentiable — BERT's train step
    auto-selects flash inside value_and_grad on TPU. Gradients of the Pallas
    flash-2 backward vs the dense reference, interpret mode on CPU."""

    @pytest.fixture(scope="class")
    def small_qkv(self, cpu0):
        with jax.default_device(cpu0):
            key = jax.random.PRNGKey(11)
            b, s, h, d = 1, 256, 1, 32
            return tuple(
                jax.random.normal(k, (b, s, h, d), jnp.float32)
                for k in jax.random.split(key, 3)
            )

    @pytest.mark.parametrize("causal", [False, True])
    def test_grads_match_reference(self, small_qkv, cpu0, causal):
        q, k, v = small_qkv
        with jax.default_device(cpu0):
            def loss_flash(q, k, v):
                return jnp.sum(
                    flash_attention(q, k, v, causal=causal,
                                    interpret=True) ** 2
                )

            def loss_ref(q, k, v):
                return jnp.sum(
                    reference_attention(q, k, v, causal=causal) ** 2
                )

            gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
            gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gr):
            denom = jnp.max(jnp.abs(b))
            assert jnp.max(jnp.abs(a - b)) / denom < 1e-4

    def test_grads_small_blocks(self, small_qkv, cpu0):
        # block 64 < seq 256: the accumulators fold multiple blocks on both
        # grid axes in the backward passes too.
        q, k, v = small_qkv
        with jax.default_device(cpu0):
            def loss_flash(q, k, v):
                return jnp.sum(
                    flash_attention(q, k, v, causal=True, block_q=64,
                                    block_k=64, interpret=True) ** 2
                )

            def loss_ref(q, k, v):
                return jnp.sum(reference_attention(q, k, v, causal=True) ** 2)

            gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
            gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gr):
            assert jnp.max(jnp.abs(a - b)) / jnp.max(jnp.abs(b)) < 1e-4

    def test_grad_under_shard_map(self, cpu0):
        # custom_vjp must compose with the shard_map placement wrapper —
        # the sharded train step differentiates through _sharded_flash.
        from cron_operator_tpu.parallel.mesh import mesh_for_devices

        mesh = mesh_for_devices(jax.devices("cpu"))  # 8-way data axis
        key = jax.random.PRNGKey(5)
        q, k, v = (
            jax.random.normal(kk, (8, 128, 1, 32), jnp.float32)
            for kk in jax.random.split(key, 3)
        )

        def loss_flash(q, k, v):
            return jnp.sum(multi_head_attention(
                q, k, v, causal=True, impl="flash", mesh=mesh,
                interpret=True,
            ) ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(reference_attention(q, k, v, causal=True) ** 2)

        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gr):
            assert jnp.max(jnp.abs(a - b)) / jnp.max(jnp.abs(b)) < 1e-4

    def test_bert_train_grads_flash_vs_xla(self, cpu0):
        """The done-criterion from VERDICT r2 #2: jax.grad through BERT with
        attention=flash matches the xla path numerically."""
        import numpy as np

        from cron_operator_tpu.models.bert import Bert, BertConfig

        with jax.default_device(cpu0):
            ids = jnp.asarray(
                np.random.RandomState(0).randint(0, 1024, (2, 128))
            )
            grads = {}
            for impl in ("flash", "xla"):
                cfg = BertConfig.tiny(
                    dtype=jnp.float32, attention_impl=impl,
                    attention_interpret=(impl == "flash"),
                )
                model = Bert(cfg)
                params = model.init(jax.random.PRNGKey(0), ids)

                def loss(p):
                    logits = model.apply(p, ids)
                    return jnp.mean(
                        jnp.sum(jax.nn.log_softmax(logits) ** 2, axis=-1)
                    )

                grads[impl] = jax.grad(loss)(params)
        flat_f = jax.tree_util.tree_leaves(grads["flash"])
        flat_x = jax.tree_util.tree_leaves(grads["xla"])
        for a, b in zip(flat_f, flat_x):
            scale = float(jnp.max(jnp.abs(b))) or 1.0
            assert float(jnp.max(jnp.abs(a - b))) / scale < 5e-4


class TestDispatch:
    def test_xla_impl(self, qkv, cpu0):
        q, k, v = qkv
        with jax.default_device(cpu0):
            out = multi_head_attention(q, k, v, impl="xla")
            ref = reference_attention(q, k, v)
        assert jnp.max(jnp.abs(out - ref)) == 0.0

    def test_auto_off_tpu_is_xla(self, qkv, cpu0):
        # On the CPU test platform auto must not pick the pallas kernel.
        q, k, v = qkv
        with jax.default_device(cpu0):
            out = multi_head_attention(q, k, v, impl="auto", mesh=None)
        assert out.shape == q.shape

    def test_ring_requires_mesh(self, qkv):
        q, k, v = qkv
        with pytest.raises(ValueError, match="needs a mesh"):
            multi_head_attention(q, k, v, impl="ring")

    def test_unknown_impl(self, qkv):
        q, k, v = qkv
        with pytest.raises(ValueError, match="unknown attention impl"):
            multi_head_attention(q, k, v, impl="nope")
