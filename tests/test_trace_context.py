"""Cross-process trace context: the W3C-shaped ``traceparent`` codec,
the ambient contextvar plumbing, front-door span handling over real HTTP
(router and shard roles), the ``"tc"`` stamp on WAL frames (and the
follower applying legacy gen-only frames unchanged), and the counted
ingest path for spans shipped home by runner subprocesses.

The hard requirements pinned here:

- a malformed or oversized ``traceparent`` degrades to "no trace" — the
  request is served and the connection survives;
- untraced reads stay exactly as cheap as before (no spans, no WAL key);
- legacy WAL frames (gen-only, pre-trace) and traced frames both apply
  on a follower byte-for-byte;
- a corrupt span frame from a peer is dropped and COUNTED
  (``trace_spans_dropped_total{reason="ingest"}``), never raised.
"""

import http.client
import json
import time

import pytest

from cron_operator_tpu.runtime.apiserver_http import HTTPAPIServer
from cron_operator_tpu.runtime.kube import APIServer
from cron_operator_tpu.runtime.manager import Metrics
from cron_operator_tpu.runtime.persistence import Persistence
from cron_operator_tpu.runtime.shard import FollowerReplica
from cron_operator_tpu.telemetry.trace import (
    CRITICAL_PATH_HOPS,
    TRACEPARENT_HEADER,
    TraceContext,
    Tracer,
    critical_path,
    current_trace,
    current_trace_id,
    format_traceparent,
    new_span_id,
    new_trace_id,
    parse_traceparent,
    reset_current_trace,
    set_current_trace,
    stitch_trace,
)

CRON_AV = "apps.kubedl.io/v1alpha1"


def wait_for(cond, timeout=5.0):
    """Spans that wrap the whole request (commit, route) are recorded
    *after* the response bytes hit the socket, so a client-side
    assertion races the handler thread's last few microseconds — poll
    instead of asserting the instant the response lands."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.01)
    return cond()


def make_cron(name):
    return {
        "apiVersion": CRON_AV, "kind": "Cron",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {"schedule": "@every 1h", "template": {"workload": {
            "apiVersion": "kubeflow.org/v1", "kind": "JAXJob",
            "spec": {}}}},
    }


class TestTraceparentCodec:
    def test_round_trip_native_ids(self):
        tid, sid = new_trace_id(), new_span_id()
        header = format_traceparent(tid, sid)
        assert len(header) == 55  # exact W3C field widths
        assert parse_traceparent(header) == TraceContext(tid, sid)

    def test_foreign_full_width_ids_pass_through(self):
        # A genuine 32-hex trace id (from a W3C tracer) must not be
        # shrunk by the padding strip.
        header = f"00-{'ab' * 16}-{'cd' * 8}-01"
        ctx = parse_traceparent(header)
        assert ctx == TraceContext("ab" * 16, "cd" * 8)

    @pytest.mark.parametrize("bad", [
        None,
        "",
        123,
        "00-" + "a" * 32 + "-" + "b" * 16,          # 3 segments
        "01-" + "a" * 32 + "-" + "b" * 16 + "-01",  # unknown version
        "00-" + "a" * 31 + "-" + "b" * 16 + "-01",  # short trace id
        "00-" + "a" * 32 + "-" + "b" * 15 + "-01",  # short span id
        "00-" + "A" * 32 + "-" + "b" * 16 + "-01",  # uppercase hex
        "00-" + "g" * 32 + "-" + "b" * 16 + "-01",  # non-hex
        "00-" + "0" * 32 + "-" + "b" * 16 + "-01",  # all-zero trace
        "00-" + "a" * 32 + "-" + "0" * 16 + "-01",  # all-zero span
        "x" * 100,                                   # oversized garbage
        format_traceparent("a" * 16, "b" * 8) + "-extra-tail",
    ])
    def test_malformed_returns_none(self, bad):
        assert parse_traceparent(bad) is None

    def test_ambient_set_get_reset(self):
        assert current_trace() is None
        assert current_trace_id() is None
        ctx = TraceContext(new_trace_id(), new_span_id())
        token = set_current_trace(ctx)
        try:
            assert current_trace() == ctx
            assert current_trace_id() == ctx.trace_id
        finally:
            reset_current_trace(token)
        assert current_trace() is None


class TestFrontDoorPropagation:
    """Trace context over real HTTP framing, shard and router roles."""

    def _post(self, srv, name, headers=None):
        conn = http.client.HTTPConnection(
            srv._server.server_address[0], srv.port, timeout=10)
        try:
            conn.request(
                "POST", f"/apis/{CRON_AV}/namespaces/default/crons",
                body=json.dumps(make_cron(name)).encode(),
                headers=headers or {},
            )
            resp = conn.getresponse()
            body = resp.read()
            return resp.status, body
        finally:
            conn.close()

    def _get(self, srv, path, headers=None):
        conn = http.client.HTTPConnection(
            srv._server.server_address[0], srv.port, timeout=10)
        try:
            conn.request("GET", path, headers=headers or {})
            resp = conn.getresponse()
            return resp.status, resp.read()
        finally:
            conn.close()

    def test_shard_spans_and_wal_tc_stamp(self, tmp_path):
        api = APIServer()
        wal = Persistence(str(tmp_path), flush_interval_s=0)
        wal.open()
        api.attach_persistence(wal)
        tracer = Tracer()
        srv = HTTPAPIServer(api=api, tracer=tracer, trace_role="shard")
        srv.start()
        try:
            tid, caller_span = new_trace_id(), new_span_id()
            status, _ = self._post(srv, "traced", headers={
                TRACEPARENT_HEADER: format_traceparent(tid, caller_span),
            })
            assert status == 201
            assert wait_for(lambda: {"admit", "commit", "fsync"} <= {
                s["name"] for s in tracer.spans(tid)})
            spans = {s["name"]: s for s in tracer.spans(tid)}
            # Parent/child crosses the process boundary via the header.
            assert spans["admit"]["parent_id"] == caller_span
            assert spans["commit"]["parent_id"] == spans["admit"]["span_id"]
            assert spans["fsync"]["parent_id"] == spans["commit"]["span_id"]
        finally:
            srv.stop()
            wal.close()
        # The committed WAL record carries the trace id next to "gen".
        with open(wal._wal_path) as f:
            recs = [json.loads(ln) for ln in f if ln.strip()]
        assert any(r.get("tc") == tid for r in recs)

    def test_write_without_header_mints_trace_on_shard(self):
        tracer = Tracer()
        srv = HTTPAPIServer(api=APIServer(), tracer=tracer,
                            trace_role="shard")
        srv.start()
        try:
            status, _ = self._post(srv, "minted")
            assert status == 201
            assert wait_for(lambda: {"admit", "commit"} <= {
                s["name"] for s in tracer.spans()})
        finally:
            srv.stop()

    @pytest.mark.parametrize("bad_header", [
        "not-a-traceparent",
        "00-zzzz-1-01",
        "00-" + "0" * 32 + "-" + "1" * 16 + "-01",
        "00-" + "a" * 200 + "-" + "b" * 16 + "-01",  # oversized
    ])
    def test_malformed_header_served_untraced(self, bad_header):
        """A garbage traceparent must not kill the request, the
        connection, or adopt a bogus trace — it degrades to the
        front-door-minted trace a headerless write gets."""
        tracer = Tracer()
        srv = HTTPAPIServer(api=APIServer(), tracer=tracer,
                            trace_role="shard")
        srv.start()
        try:
            status, _ = self._post(
                srv, "survives", headers={TRACEPARENT_HEADER: bad_header})
            assert status == 201
            # No span adopted the (unparseable) caller context.
            assert all(
                s["parent_id"] is None or s["parent_id"] != bad_header
                for s in tracer.spans()
            )
            assert all(s["name"] != "route" for s in tracer.spans())
            # The connection machinery survived: a second request works.
            status, _ = self._post(srv, "survives-2")
            assert status == 201
        finally:
            srv.stop()

    def test_untraced_read_records_nothing(self):
        tracer = Tracer()
        srv = HTTPAPIServer(api=APIServer(), tracer=tracer,
                            trace_role="shard")
        srv.start()
        try:
            status, _ = self._get(
                srv, f"/apis/{CRON_AV}/namespaces/default/crons")
            assert status == 200
            assert tracer.spans() == []  # zero-cost steady state
        finally:
            srv.stop()

    def test_traced_read_records_admit_only(self):
        tracer = Tracer()
        srv = HTTPAPIServer(api=APIServer(), tracer=tracer,
                            trace_role="shard")
        srv.start()
        try:
            tid = new_trace_id()
            status, _ = self._get(
                srv, f"/apis/{CRON_AV}/namespaces/default/crons",
                headers={TRACEPARENT_HEADER:
                         format_traceparent(tid, new_span_id())})
            assert status == 200
            assert wait_for(lambda: tracer.spans(tid))
            assert [s["name"] for s in tracer.spans(tid)] == ["admit"]
        finally:
            srv.stop()

    def test_router_role_records_one_route_span(self):
        tracer = Tracer()
        tracer.set_proc(role="router")
        srv = HTTPAPIServer(api=APIServer(), tracer=tracer,
                            trace_role="router")
        srv.start()
        try:
            tid = new_trace_id()
            status, _ = self._post(srv, "via-router", headers={
                TRACEPARENT_HEADER: format_traceparent(tid, new_span_id()),
            })
            assert status == 201
            assert wait_for(lambda: tracer.spans(tid))
            spans = tracer.spans(tid)
            assert [s["name"] for s in spans] == ["route"]
            assert spans[0]["attrs"]["proc"] == "router"
        finally:
            srv.stop()


class TestFollowerFrames:
    """WAL-ship wire compatibility: gen-only (legacy) and tc-stamped
    frames both apply; corrupt frames are counted, not fatal."""

    def _frame(self, rec):
        return (json.dumps(rec, separators=(",", ":")) + "\n").encode()

    def _put_rec(self, name, rv, **extra):
        return dict({
            "op": "put",
            "obj": {"apiVersion": "v1", "kind": "Pod",
                    "metadata": {"name": name, "namespace": "default",
                                 "resourceVersion": str(rv)}},
        }, **extra)

    def test_legacy_gen_only_frame_applies(self):
        follower = FollowerReplica()
        follower.apply_bytes(self._frame(self._put_rec("legacy", 1, gen=3)))
        assert follower.records_applied == 1
        assert follower.generation == 3

    def test_tc_frame_applies_and_records_wal_apply_span(self):
        tracer = Tracer()
        follower = FollowerReplica(tracer=tracer)
        tid = new_trace_id()
        follower.apply_bytes(
            self._frame(self._put_rec("traced", 1, gen=1, tc=tid)))
        assert follower.records_applied == 1
        spans = tracer.spans(tid)
        assert [s["name"] for s in spans] == ["wal_apply"]
        assert spans[0]["attrs"]["op"] == "put"

    def test_tc_frame_without_tracer_still_applies(self):
        follower = FollowerReplica()
        follower.apply_bytes(
            self._frame(self._put_rec("traced", 1, tc=new_trace_id())))
        assert follower.records_applied == 1

    def test_corrupt_frame_counted_not_fatal(self):
        follower = FollowerReplica(tracer=Tracer())
        follower.apply_bytes(b'{"op": "put", "obj": \n')
        follower.apply_bytes(self._frame(self._put_rec("after", 2)))
        assert follower.records_dropped == 1
        assert follower.records_applied == 1


class TestIngest:
    def _span(self, **over):
        base = {
            "name": "runner", "trace_id": new_trace_id(),
            "span_id": new_span_id(), "parent_id": None,
            "start_s": 100.0, "end_s": 101.0,
            "attrs": {"pid": 4242, "proc": "runner"},
        }
        base.update(over)
        return base

    def test_valid_spans_adopted_with_origin_attrs(self):
        metrics = Metrics()
        tracer = Tracer(metrics=metrics)
        tracer.set_proc(role="shard")  # must NOT restamp ingested spans
        good = self._span()
        assert tracer.ingest([good]) == 1
        (span,) = tracer.spans(good["trace_id"])
        assert span["attrs"]["pid"] == 4242  # origin identity kept
        assert span["attrs"]["proc"] == "runner"
        assert tracer.spans_dropped == 0

    @pytest.mark.parametrize("bad", [
        {"trace_id": "t"},                            # no name
        {"name": "", "trace_id": "t", "start_s": 1, "end_s": 2},
        {"name": "x", "trace_id": "", "start_s": 1, "end_s": 2},
        {"name": "x", "trace_id": "t", "start_s": 2, "end_s": 1},
        {"name": "x", "trace_id": "t", "start_s": "nan?", "end_s": 2},
        {"name": "x", "trace_id": "t", "start_s": 1, "end_s": 2,
         "attrs": "not-a-dict"},
        "not even a dict",
        None,
    ])
    def test_bad_frames_dropped_and_counted(self, bad):
        metrics = Metrics()
        tracer = Tracer(metrics=metrics)
        assert tracer.ingest([bad]) == 0
        assert tracer.spans_dropped == 1
        assert metrics.get(
            'trace_spans_dropped_total{reason="ingest"}') == 1
        assert tracer.spans() == []

    def test_mixed_batch_counts_only_bad(self):
        metrics = Metrics()
        tracer = Tracer(metrics=metrics)
        assert tracer.ingest([self._span(), {"junk": 1}, self._span()]) == 2
        assert tracer.spans_dropped == 1
        assert len(tracer.spans()) == 2


class TestAssembly:
    def _hop(self, name, t0, t1, tid, parent=None, **attrs):
        return {"name": name, "trace_id": tid, "span_id": new_span_id(),
                "parent_id": parent, "start_s": t0, "end_s": t1,
                "attrs": attrs}

    def test_stitch_dedupes_and_counts_processes(self):
        tid = new_trace_id()
        a = self._hop("route", 0.0, 1.0, tid, pid=1, proc="router")
        b = self._hop("admit", 0.1, 0.2, tid, parent=a["span_id"],
                      pid=2, proc="shard")
        # The router fan-in naturally sees its own copy of a twice.
        doc = stitch_trace([[a, b], [a]], tid)
        assert len(doc["spans"]) == 2
        assert doc["processes"] == [
            {"pid": 1, "proc": "router"}, {"pid": 2, "proc": "shard"}]
        assert doc["orphans"] == []

    def test_stitch_flags_orphans(self):
        tid = new_trace_id()
        lost = self._hop("commit", 0.0, 1.0, tid, parent="dead-beef")
        doc = stitch_trace([[lost]], tid)
        assert doc["orphans"] == [lost["span_id"]]

    def test_critical_path_partitions_wall_with_gap(self):
        tid = new_trace_id()
        spans = [
            self._hop("route", 0.0, 1.0, tid),
            self._hop("admit", 0.1, 0.9, tid),    # inner hop owns slice
            self._hop("commit", 0.2, 0.5, tid),   # innermost wins
            self._hop("fsync", 0.5, 0.6, tid),
            self._hop("submit", 2.0, 2.5, tid),   # 1.0→2.0 is a gap
            self._hop("first_step", 2.5, 3.0, tid),
        ]
        cp = critical_path(spans)
        assert cp["missing"] == []
        assert cp["reconciles"] is True
        by_hop = {h["hop"]: h["seconds"] for h in cp["hops"]}
        assert by_hop["(gap)"] == pytest.approx(1.0)
        assert by_hop["commit"] == pytest.approx(0.3)
        assert sum(by_hop.values()) == pytest.approx(cp["wall_s"])
        # Canonical order, gap last.
        order = [h["hop"] for h in cp["hops"]]
        assert order == [*CRITICAL_PATH_HOPS, "(gap)"]

    def test_critical_path_missing_hop_fails_reconcile(self):
        tid = new_trace_id()
        cp = critical_path([self._hop("route", 0.0, 1.0, tid)])
        assert "first_step" in cp["missing"]
        assert cp["reconciles"] is False
