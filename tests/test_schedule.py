"""Cron schedule engine tests — semantics parity with robfig/cron/v3
ParseStandard (the reference's parser, ``cron_controller.go:392``)."""

from datetime import datetime, timedelta, timezone

import pytest

from cron_operator_tpu.controller.schedule import (
    EverySchedule,
    parse_go_duration,
    parse_standard,
)


def utc(*args):
    return datetime(*args, tzinfo=timezone.utc)


class TestParseErrors:
    @pytest.mark.parametrize(
        "expr",
        [
            "",
            "* * * *",  # 4 fields
            "* * * * * *",  # 6 fields (no seconds in standard)
            "60 * * * *",  # minute out of range
            "* 24 * * *",  # hour out of range
            "* * 0 * *",  # dom out of range
            "* * * 13 *",  # month out of range
            "* * * * 8",  # dow out of range
            "*/0 * * * *",  # zero step
            "a * * * *",  # garbage
            "@reboot",  # unsupported descriptor
            "@every",  # missing duration
            "1-0 * * * *",  # inverted range
        ],
    )
    def test_invalid(self, expr):
        with pytest.raises(ValueError):
            parse_standard(expr)

    def test_valid_do_not_raise(self):
        for expr in [
            "* * * * *",
            "*/5 * * * *",
            "0 0 1 1 *",
            "0 9-17 * * MON-FRI",
            "15,45 */2 1-15 JAN,jul *",
            "0 0 * * 7",  # 7 == Sunday
            "@hourly",
            "@daily",
            "@weekly",
            "@monthly",
            "@yearly",
            "@annually",
            "@midnight",
            "@every 90s",
            "@every 1h30m",
        ]:
            parse_standard(expr)


class TestNext:
    def test_every_minute(self):
        s = parse_standard("* * * * *")
        assert s.next(utc(2026, 3, 1, 10, 30, 15)) == utc(2026, 3, 1, 10, 31)

    def test_strictly_after(self):
        s = parse_standard("* * * * *")
        # exactly on an activation → the next one
        assert s.next(utc(2026, 3, 1, 10, 30)) == utc(2026, 3, 1, 10, 31)

    def test_every_5_minutes(self):
        s = parse_standard("*/5 * * * *")
        assert s.next(utc(2026, 3, 1, 10, 2)) == utc(2026, 3, 1, 10, 5)
        assert s.next(utc(2026, 3, 1, 10, 5)) == utc(2026, 3, 1, 10, 10)
        assert s.next(utc(2026, 3, 1, 23, 58)) == utc(2026, 3, 2, 0, 0)

    def test_hour_rollover(self):
        s = parse_standard("30 14 * * *")
        assert s.next(utc(2026, 3, 1, 15, 0)) == utc(2026, 3, 2, 14, 30)
        assert s.next(utc(2026, 3, 1, 14, 0)) == utc(2026, 3, 1, 14, 30)

    def test_month_names_and_rollover(self):
        s = parse_standard("0 0 1 mar *")
        assert s.next(utc(2026, 3, 5)) == utc(2027, 3, 1)
        assert s.next(utc(2026, 1, 5)) == utc(2026, 3, 1)

    def test_dow(self):
        # Sunday (2026-03-01 is a Sunday)
        s = parse_standard("0 12 * * SUN")
        assert s.next(utc(2026, 3, 1, 13, 0)) == utc(2026, 3, 8, 12, 0)
        assert s.next(utc(2026, 2, 28)) == utc(2026, 3, 1, 12, 0)

    def test_dow_7_is_sunday(self):
        a = parse_standard("0 12 * * 0")
        b = parse_standard("0 12 * * 7")
        t = utc(2026, 3, 2)
        assert a.next(t) == b.next(t)

    def test_vixie_dom_dow_or_rule(self):
        # Both restricted: fires on the 15th OR on Mondays.
        s = parse_standard("0 0 15 * MON")
        # 2026-03-01 Sun → next is Mon 2026-03-02
        assert s.next(utc(2026, 3, 1, 1, 0)) == utc(2026, 3, 2, 0, 0)
        # From Mon 3-02 00:30 → Mon 3-09? no — dom 15 vs next Monday 3-09: min is 3-09
        assert s.next(utc(2026, 3, 2, 0, 30)) == utc(2026, 3, 9, 0, 0)
        # From 3-13 (Fri) → dom 15 (Sunday 3-15) before Monday 3-16
        assert s.next(utc(2026, 3, 13)) == utc(2026, 3, 15, 0, 0)

    def test_dom_restricted_only(self):
        s = parse_standard("0 0 15 * *")
        assert s.next(utc(2026, 3, 1)) == utc(2026, 3, 15)

    def test_step_range(self):
        s = parse_standard("10-30/10 * * * *")
        assert s.next(utc(2026, 3, 1, 9, 0)) == utc(2026, 3, 1, 9, 10)
        assert s.next(utc(2026, 3, 1, 9, 10)) == utc(2026, 3, 1, 9, 20)
        assert s.next(utc(2026, 3, 1, 9, 30)) == utc(2026, 3, 1, 10, 10)

    def test_leap_day(self):
        s = parse_standard("0 0 29 2 *")
        assert s.next(utc(2026, 1, 1)) == utc(2028, 2, 29)

    def test_unschedulable_raises(self):
        s = parse_standard("0 0 31 2 *")  # Feb 31 never exists
        with pytest.raises(ValueError):
            s.next(utc(2026, 1, 1))

    def test_descriptor_hourly(self):
        s = parse_standard("@hourly")
        assert s.next(utc(2026, 3, 1, 10, 30)) == utc(2026, 3, 1, 11, 0)

    def test_every_schedule(self):
        s = parse_standard("@every 90s")
        assert isinstance(s, EverySchedule)
        assert s.next(utc(2026, 3, 1, 10, 0, 0)) == utc(2026, 3, 1, 10, 1, 30)

    def test_preserves_timezone(self):
        from zoneinfo import ZoneInfo

        tz = ZoneInfo("America/New_York")
        s = parse_standard("0 9 * * *")
        t = datetime(2026, 3, 2, 10, 0, tzinfo=tz)
        nxt = s.next(t)
        assert nxt.hour == 9 and nxt.day == 3
        assert nxt.tzinfo is tz


class TestGoDuration:
    def test_units(self):
        assert parse_go_duration("90s") == timedelta(seconds=90)
        assert parse_go_duration("1h30m") == timedelta(hours=1, minutes=30)
        assert parse_go_duration("250ms") == timedelta(milliseconds=250)

    def test_invalid(self):
        for bad in ["", "5", "h", "1x"]:
            with pytest.raises(ValueError):
                parse_go_duration(bad)


class TestReviewRegressions:
    """Fixes from code review: dow step across the 7-wrap, '@every -'."""

    def test_dow_range_with_step_ending_at_7(self):
        from datetime import datetime, timezone

        s = parse_standard("0 0 * * 4-7/2")  # Thu, Sat... 7 unreachable by step
        # mask: 4(Thu), 6(Sat) — never Sunday, never Friday
        hits = []
        t = datetime(2026, 3, 1, tzinfo=timezone.utc)
        for _ in range(6):
            t = s.next(t)
            hits.append(t.strftime("%a"))
        assert set(hits) == {"Thu", "Sat"}

    def test_dow_range_step_reaching_7_maps_to_sunday(self):
        from datetime import datetime, timezone

        s = parse_standard("0 0 * * 5-7/2")  # Fri, Sun
        hits = []
        t = datetime(2026, 3, 1, tzinfo=timezone.utc)
        for _ in range(6):
            t = s.next(t)
            hits.append(t.strftime("%a"))
        assert set(hits) == {"Fri", "Sun"}

    def test_bare_dash_duration_rejected(self):
        import pytest as _pytest

        with _pytest.raises(ValueError):
            parse_go_duration("-")
        with _pytest.raises(ValueError):
            parse_standard("@every -")


class TestBackoffClamp:
    def test_no_overflow_on_persistent_failure(self):
        from cron_operator_tpu.runtime.workqueue import ItemExponentialBackoff

        b = ItemExponentialBackoff()
        for _ in range(1200):
            delay = b.when("stuck")
        assert delay == b.cap_s


class TestScheduleProperties:
    """Property-style checks over randomized cron expressions — the
    from-scratch robfig equivalent must satisfy the cron invariants for
    ANY valid expression, not just the handwritten cases above."""

    def _random_exprs(self, n=200, seed=42):
        import random

        rng = random.Random(seed)

        def field(lo, hi):
            kind = rng.randrange(4)
            if kind == 0:
                return "*"
            if kind == 1:
                return str(rng.randint(lo, hi))
            if kind == 2:  # range
                a = rng.randint(lo, hi - 1)
                b = rng.randint(a, hi)
                return f"{a}-{b}"
            step = rng.randint(2, 15)
            return f"*/{step}"

        out = []
        for _ in range(n):
            out.append(" ".join([
                field(0, 59), field(0, 23), field(1, 31),
                field(1, 12), field(0, 6),
            ]))
        return out

    def test_next_is_strictly_future_and_matches_fields(self):
        from datetime import datetime, timezone

        start = datetime(2026, 3, 14, 15, 9, 26, tzinfo=timezone.utc)
        for expr in self._random_exprs():
            sched = parse_standard(expr)
            t = start
            for _ in range(3):
                nxt = sched.next(t)
                assert nxt > t, f"{expr}: next not in the future"
                assert nxt.second == 0, f"{expr}: minute granularity"
                # The activation instant must satisfy every field.
                mi, hr, dom, mon, dow = expr.split()
                for val, spec, lo in [
                    (nxt.minute, mi, 0), (nxt.hour, hr, 0),
                    (nxt.month, mon, 1),
                ]:
                    assert self._matches(val, spec, lo), (
                        f"{expr}: {val} fails {spec} at {nxt}"
                    )
                t = nxt

    @staticmethod
    def _matches(value, spec, lo=0):
        if spec == "*":
            return True
        if spec.startswith("*/"):
            # steps count from the field's lower bound (vixie cron):
            # months */11 over 1..12 matches {1, 12}.
            return (value - lo) % int(spec[2:]) == 0
        if "-" in spec:
            a, b = spec.split("-")
            return int(a) <= value <= int(b)
        return value == int(spec)

    def test_next_is_minimal(self):
        """Consistency of "first match": for any probe strictly between t
        and next(t), next(probe) must still be next(t) — if a nearer
        match existed the two calls would disagree."""
        from datetime import datetime, timedelta, timezone

        start = datetime(2026, 6, 1, 0, 0, tzinfo=timezone.utc)
        for expr in self._random_exprs(n=30, seed=7):
            sched = parse_standard(expr)
            nxt = sched.next(start)
            span_min = int((nxt - start).total_seconds() // 60)
            # a handful of probes across the gap (bounded for huge gaps)
            for k in {1, 2, span_min // 2, span_min - 1} - {0}:
                if k >= span_min:
                    continue
                probe = start + timedelta(minutes=k)
                assert sched.next(probe) == nxt, (
                    f"{expr}: next({probe}) != next({start})"
                )

    def test_dom_dow_vixie_or_rule(self):
        """Standard cron quirk: when BOTH day-of-month and day-of-week are
        restricted, a time matching EITHER fires (vixie OR rule)."""
        from datetime import datetime, timezone

        sched = parse_standard("0 0 13 * 5")  # 13th OR Friday
        t = datetime(2026, 2, 1, tzinfo=timezone.utc)
        fired_days = set()
        for _ in range(12):
            t = sched.next(t)
            fired_days.add((t.day, t.weekday()))
        assert any(d == 13 for d, _ in fired_days)
        assert any(w == 4 for _, w in fired_days)  # Friday
        for d, w in fired_days:
            assert d == 13 or w == 4
