"""Test bootstrap: force JAX onto a virtual 8-device CPU mesh so multi-chip
sharding paths compile and execute without TPU hardware (the dryrun strategy
from the build brief; mirrors how the reference tests multi-node behavior
against envtest without a real cluster — SURVEY.md §4)."""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# Force CPU-only via config, which beats both the env var and any TPU
# plugin's own config.update (some environments register a tunneled TPU
# backend at interpreter startup; unit tests must never touch it — the
# real chip is for bench.py).
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

from cron_operator_tpu.runtime.kube import APIServer  # noqa: E402
from cron_operator_tpu.utils.clock import FakeClock  # noqa: E402


@pytest.fixture
def fake_clock():
    return FakeClock()


@pytest.fixture
def api(fake_clock):
    """An empty embedded control plane on a deterministic clock."""
    server = APIServer(clock=fake_clock)
    yield server
    server.close()  # stop the watch dispatcher; no thread leak per test
