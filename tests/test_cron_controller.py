"""Cron reconciler behavior specs — the envtest suite analog
(reference ``internal/controller/cron_controller_test.go`` and
``cron_util_test.go`` scenarios, driven against the embedded control plane
with a deterministic clock)."""

from datetime import datetime, timedelta, timezone

import pytest

from cron_operator_tpu.api.v1alpha1 import (
    API_VERSION,
    KIND_CRON,
    LABEL_CRON_NAME,
)
from cron_operator_tpu.controller.cron_controller import CronReconciler
from cron_operator_tpu.controller.workload import (
    WorkloadTemplateError,
    get_default_job_name,
    new_empty_workload,
)

T0 = datetime(2026, 1, 1, tzinfo=timezone.utc)
JAX_AV, JAX_KIND = "kubeflow.org/v1", "JAXJob"


def jax_template(name=None):
    tpl = {
        "apiVersion": JAX_AV,
        "kind": JAX_KIND,
        "metadata": {},
        "spec": {"replicaSpecs": {"Worker": {"replicas": 1}}},
    }
    if name:
        tpl["metadata"]["name"] = name
    return tpl


def make_cron(
    api,
    name="demo",
    schedule="*/1 * * * *",
    policy=None,
    suspend=None,
    deadline=None,
    history_limit=None,
    template=None,
):
    spec = {"schedule": schedule, "template": {"workload": template or jax_template()}}
    if policy:
        spec["concurrencyPolicy"] = policy
    if suspend is not None:
        spec["suspend"] = suspend
    if deadline is not None:
        spec["deadline"] = deadline
    if history_limit is not None:
        spec["historyLimit"] = history_limit
    return api.create(
        {
            "apiVersion": API_VERSION,
            "kind": KIND_CRON,
            "metadata": {"name": name, "namespace": "default"},
            "spec": spec,
        }
    )


def get_cron(api, name="demo"):
    return api.get(API_VERSION, KIND_CRON, "default", name)


def list_jobs(api):
    return api.list(JAX_AV, JAX_KIND, namespace="default")


def finish_job(api, name, cond="Succeeded"):
    api.patch_status(
        JAX_AV, JAX_KIND, "default", name,
        {"conditions": [
            {"type": "Created", "status": "True"},
            {"type": cond, "status": "True"},
        ]},
    )


@pytest.fixture
def reconciler(api):
    return CronReconciler(api)


class TestBasicReconcile:
    def test_not_found_is_noop(self, reconciler):
        result = reconciler.reconcile("default", "ghost")
        assert result.requeue_after is None

    def test_no_tick_due_requeues_at_next(self, api, fake_clock, reconciler):
        make_cron(api)  # created at T0
        result = reconciler.reconcile("default", "demo")
        # next activation is T0+1min
        assert result.requeue_after == timedelta(minutes=1)
        assert list_jobs(api) == []

    def test_schedule_fires_creates_workload(self, api, fake_clock, reconciler):
        make_cron(api)
        fake_clock.advance(timedelta(minutes=2))
        result = reconciler.reconcile("default", "demo")
        jobs = list_jobs(api)
        assert len(jobs) == 1
        job = jobs[0]
        meta = job["metadata"]
        # deterministic name derived from *nextRun* (reference quirk,
        # cron_controller.go:222)
        next_run = T0 + timedelta(minutes=3)
        assert meta["name"] == f"demo-{int(next_run.timestamp())}"
        assert meta["labels"][LABEL_CRON_NAME] == "demo"
        owner = meta["ownerReferences"][0]
        assert owner["kind"] == KIND_CRON and owner["controller"] is True
        # status updated
        cron = get_cron(api)
        assert cron["status"]["lastScheduleTime"] == "2026-01-01T00:02:00Z"
        assert result.requeue_after == timedelta(minutes=1)

    def test_tick_is_idempotent(self, api, fake_clock, reconciler):
        make_cron(api)
        fake_clock.advance(timedelta(minutes=2))
        reconciler.reconcile("default", "demo")
        # Second reconcile in the same instant: name collides → tolerated,
        # no duplicate.
        reconciler.reconcile("default", "demo")
        assert len(list_jobs(api)) == 1

    def test_new_tick_after_interval(self, api, fake_clock, reconciler):
        make_cron(api)
        fake_clock.advance(timedelta(minutes=2))
        reconciler.reconcile("default", "demo")
        fake_clock.advance(timedelta(minutes=2))
        reconciler.reconcile("default", "demo")
        assert len(list_jobs(api)) == 2


class TestGates:
    def test_suspend_no_workload_no_requeue(self, api, fake_clock, reconciler):
        make_cron(api, suspend=True)
        fake_clock.advance(timedelta(minutes=5))
        result = reconciler.reconcile("default", "demo")
        assert list_jobs(api) == []
        assert result.requeue_after is None

    def test_deadline_stops_scheduling(self, api, fake_clock, reconciler):
        make_cron(api, deadline="2026-01-01T00:03:00Z")
        fake_clock.advance(timedelta(minutes=5))
        result = reconciler.reconcile("default", "demo")
        assert list_jobs(api) == []
        assert result.requeue_after is None
        assert len(api.events(reason="Deadline")) == 1

    def test_deadline_in_future_schedules(self, api, fake_clock, reconciler):
        make_cron(api, deadline="2026-01-01T00:10:00Z")
        fake_clock.advance(timedelta(minutes=2))
        reconciler.reconcile("default", "demo")
        assert len(list_jobs(api)) == 1

    def test_unparsable_schedule_terminal(self, api, fake_clock, reconciler):
        make_cron(api, schedule="not a cron")
        fake_clock.advance(timedelta(minutes=2))
        result = reconciler.reconcile("default", "demo")
        assert result.requeue_after is None
        assert list_jobs(api) == []

    def test_unschedulable_schedule_terminal(self, api, fake_clock, reconciler):
        make_cron(api, schedule="0 0 31 2 *")  # Feb 31
        fake_clock.advance(timedelta(minutes=2))
        result = reconciler.reconcile("default", "demo")
        assert result.requeue_after is None

    def test_invalid_template_terminal(self, api, fake_clock, reconciler):
        make_cron(api, template={"metadata": {"name": "x"}})  # no GVK
        result = reconciler.reconcile("default", "demo")
        assert result.requeue_after is None


class TestConcurrencyPolicies:
    def _fire_once(self, api, fake_clock, reconciler):
        fake_clock.advance(timedelta(minutes=2))
        reconciler.reconcile("default", "demo")
        jobs = list_jobs(api)
        assert len(jobs) == 1
        return jobs[0]["metadata"]["name"]

    def test_allow_overlapping(self, api, fake_clock, reconciler):
        make_cron(api, policy="Allow")
        self._fire_once(api, fake_clock, reconciler)
        fake_clock.advance(timedelta(minutes=2))
        reconciler.reconcile("default", "demo")
        # first job still active (no terminal status) yet second created
        assert len(list_jobs(api)) == 2
        # active list is synced at reconcile start (before this tick's
        # create — reference order, cron_controller.go:155 vs :229), so the
        # second job lands in status.active on the NEXT pass.
        reconciler.reconcile("default", "demo")
        cron = get_cron(api)
        assert len(cron["status"]["active"]) == 2

    def test_forbid_skips_while_active(self, api, fake_clock, reconciler):
        make_cron(api, policy="Forbid")
        first = self._fire_once(api, fake_clock, reconciler)
        fake_clock.advance(timedelta(minutes=2))
        result = reconciler.reconcile("default", "demo")
        assert [j["metadata"]["name"] for j in list_jobs(api)] == [first]
        assert result.requeue_after is not None

    def test_forbid_fires_after_completion(self, api, fake_clock, reconciler):
        make_cron(api, policy="Forbid")
        first = self._fire_once(api, fake_clock, reconciler)
        finish_job(api, first)
        fake_clock.advance(timedelta(minutes=2))
        reconciler.reconcile("default", "demo")
        assert len(list_jobs(api)) == 2

    def test_replace_deletes_active(self, api, fake_clock, reconciler):
        make_cron(api, policy="Replace")
        first = self._fire_once(api, fake_clock, reconciler)
        fake_clock.advance(timedelta(minutes=2))
        reconciler.reconcile("default", "demo")
        jobs = list_jobs(api)
        assert len(jobs) == 1
        assert jobs[0]["metadata"]["name"] != first

    def test_replace_keeps_same_ticks_surviving_workload(
        self, api, fake_clock, reconciler
    ):
        """Fail-over guard: when a re-fired tick's own workload survived a
        crash (its lastScheduleTime update was lost), Replace must NOT
        delete-and-relaunch it — the deterministic name exists so the
        re-run collides on AlreadyExists instead of double-launching."""
        make_cron(api, policy="Replace")
        first = self._fire_once(api, fake_clock, reconciler)
        (job,) = list_jobs(api)
        uid = job["metadata"]["uid"]
        # Crash-recovered shape: the workload is durable but the status
        # update advancing lastScheduleTime was in the lost WAL suffix.
        cron = get_cron(api)
        status = dict(cron.get("status") or {})
        status.pop("lastScheduleTime", None)
        api.patch_status(
            cron["apiVersion"], cron["kind"], "default", "demo", status
        )
        reconciler.reconcile("default", "demo")  # re-fires the same tick
        (job,) = list_jobs(api)
        assert job["metadata"]["name"] == first
        assert job["metadata"]["uid"] == uid, (
            "Replace deleted and re-created this tick's own workload"
        )


class TestTPUAdmissionOnControllerPath:
    """The controller-side admission seam (VERDICT r2 #1): workloads the
    reconciler creates already carry TPU scheduling metadata, and invalid
    TPU templates never destroy a healthy Replace-policy workload."""

    def tpu_template(self, extra_ann=None):
        tpl = jax_template()
        ann = {
            "tpu.kubedl.io/accelerator": "v5e",
            "tpu.kubedl.io/topology": "4x4",
        }
        ann.update(extra_ann or {})
        tpl["metadata"]["annotations"] = ann
        return tpl

    def test_created_workload_carries_tpu_metadata(
        self, api, fake_clock, reconciler
    ):
        make_cron(api, template=self.tpu_template())
        fake_clock.advance(timedelta(minutes=2))
        reconciler.reconcile("default", "demo")
        (job,) = list_jobs(api)
        worker = job["spec"]["replicaSpecs"]["Worker"]
        assert worker["replicas"] == 4  # v5e 4x4 = 4 hosts
        sel = worker["template"]["spec"]["nodeSelector"]
        assert sel["cloud.google.com/gke-tpu-topology"] == "4x4"
        env = {e["name"] for e in
               worker["template"]["spec"]["containers"][0]["env"]}
        assert {"JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES",
                "JAX_PROCESS_ID"} <= env

    def test_invalid_tpu_template_fires_event_no_create(
        self, api, fake_clock, reconciler
    ):
        make_cron(api, template=self.tpu_template(
            {"tpu.kubedl.io/param.lr": "1", "tpu.kubedl.io/param.LR": "2"}
        ))
        fake_clock.advance(timedelta(minutes=2))
        result = reconciler.reconcile("default", "demo")
        assert list_jobs(api) == []
        assert result.requeue_after is not None  # keeps ticking
        assert any(e.reason == "FailedTPUAdmission" for e in api.events())

    def test_replace_not_destructive_on_invalid_template(
        self, api, fake_clock, reconciler
    ):
        """Replace must validate before it deletes: a healthy active job
        survives a tick whose template cannot pass admission."""
        make_cron(api, policy="Replace", template=self.tpu_template())
        fake_clock.advance(timedelta(minutes=2))
        reconciler.reconcile("default", "demo")
        (job,) = list_jobs(api)
        running = job["metadata"]["name"]
        # Break the template: two param keys that normalize identically.
        cron = get_cron(api)
        ann = cron["spec"]["template"]["workload"]["metadata"]["annotations"]
        ann["tpu.kubedl.io/param.lr"] = "1"
        ann["tpu.kubedl.io/param.LR"] = "2"
        api.update(cron)
        fake_clock.advance(timedelta(minutes=1))
        reconciler.reconcile("default", "demo")
        names = [j["metadata"]["name"] for j in list_jobs(api)]
        assert names == [running], (
            "active workload must survive failed admission"
        )


class TestStatusSync:
    def test_active_list_sorted_with_refs(self, api, fake_clock, reconciler):
        make_cron(api)
        fake_clock.advance(timedelta(minutes=2))
        reconciler.reconcile("default", "demo")
        fake_clock.advance(timedelta(minutes=2))
        reconciler.reconcile("default", "demo")
        reconciler.reconcile("default", "demo")  # fold this tick's job into active
        cron = get_cron(api)
        active = cron["status"]["active"]
        assert len(active) == 2
        assert active[0]["apiVersion"] == JAX_AV
        assert active[0]["kind"] == JAX_KIND
        assert active[0]["uid"]
        assert active[0]["resourceVersion"]
        # oldest first
        names = [a["name"] for a in active]
        assert names == sorted(names, key=lambda n: int(n.rsplit("-", 1)[1]))

    def test_finished_job_moves_to_history(self, api, fake_clock, reconciler):
        make_cron(api)
        fake_clock.advance(timedelta(minutes=2))
        reconciler.reconcile("default", "demo")
        name = list_jobs(api)[0]["metadata"]["name"]
        finish_job(api, name)
        fake_clock.advance(timedelta(seconds=10))
        reconciler.reconcile("default", "demo")
        cron = get_cron(api)
        assert cron["status"].get("active") in (None, [])
        history = cron["status"]["history"]
        assert len(history) == 1
        entry = history[0]
        assert entry["status"] == "Succeeded"
        assert entry["object"]["name"] == name
        # apiGroup carries group/version (reference back-compat quirk)
        assert entry["object"]["apiGroup"] == JAX_AV
        assert entry["finished"]  # stamped at sync time

    def test_failed_status_recorded(self, api, fake_clock, reconciler):
        make_cron(api)
        fake_clock.advance(timedelta(minutes=2))
        reconciler.reconcile("default", "demo")
        name = list_jobs(api)[0]["metadata"]["name"]
        finish_job(api, name, cond="Failed")
        reconciler.reconcile("default", "demo")
        cron = get_cron(api)
        assert cron["status"]["history"][0]["status"] == "Failed"

    def test_history_limit_gc(self, api, fake_clock, reconciler):
        make_cron(api, history_limit=2)
        names = []
        for _ in range(4):
            fake_clock.advance(timedelta(minutes=2))
            reconciler.reconcile("default", "demo")
            jobs = [
                j["metadata"]["name"] for j in list_jobs(api)
                if j["metadata"]["name"] not in names
            ]
            assert len(jobs) == 1
            names.append(jobs[0])
            finish_job(api, jobs[0])
        reconciler.reconcile("default", "demo")
        cron = get_cron(api)
        history = cron["status"]["history"]
        assert len(history) == 2
        # the two newest survive; oldest two workloads were deleted
        kept = {h["object"]["name"] for h in history}
        assert kept == set(names[-2:])
        remaining = {j["metadata"]["name"] for j in list_jobs(api)}
        assert remaining == set(names[-2:])


class TestTemplateInstantiation:
    def test_template_name_forces_forbid_event(self, api, fake_clock, reconciler):
        make_cron(api, template=jax_template(name="pinned"))
        fake_clock.advance(timedelta(minutes=2))
        reconciler.reconcile("default", "demo")
        jobs = list_jobs(api)
        assert jobs[0]["metadata"]["name"] == "pinned"
        assert len(api.events(reason="OverridePolicy")) == 1
        # in-memory override only: persisted spec still Allow default
        cron = get_cron(api)
        assert "concurrencyPolicy" not in cron["spec"] or cron["spec"][
            "concurrencyPolicy"
        ] == "Allow"

    def test_generate_name_cleared(self, api, fake_clock, reconciler):
        tpl = jax_template()
        tpl["metadata"]["generateName"] = "risky-"
        make_cron(api, template=tpl)
        fake_clock.advance(timedelta(minutes=2))
        reconciler.reconcile("default", "demo")
        meta = list_jobs(api)[0]["metadata"]
        assert meta["name"].startswith("demo-")
        assert "generateName" not in meta or not meta["generateName"]

    def test_default_job_name(self, api):
        from cron_operator_tpu.api.v1alpha1 import Cron

        cron = Cron.from_dict(
            {"metadata": {"name": "mycron", "namespace": "default"}, "spec": {}}
        )
        t = datetime(2026, 3, 1, 10, 0, tzinfo=timezone.utc)
        assert get_default_job_name(cron, t) == f"mycron-{int(t.timestamp())}"

    def test_new_empty_workload_validation(self):
        from cron_operator_tpu.api.v1alpha1 import Cron

        for tpl in [None, {"metadata": {}}, {"apiVersion": "v1"}, {"kind": "Job"}]:
            cron = Cron.from_dict(
                {
                    "metadata": {"name": "c", "namespace": "default"},
                    "spec": {"template": {"workload": tpl}},
                }
            )
            with pytest.raises(WorkloadTemplateError):
                new_empty_workload(cron)


class TestMissedRunCatchup:
    def test_too_many_missed_emits_warning(self, api, fake_clock, reconciler):
        make_cron(api)  # every minute
        fake_clock.advance(timedelta(hours=3))  # 180 missed ticks
        reconciler.reconcile("default", "demo")
        assert len(api.events(reason="TooManyMissedTimes")) == 1
        # still fires exactly one job for the catch-up
        assert len(list_jobs(api)) == 1

    def test_few_missed_no_warning(self, api, fake_clock, reconciler):
        make_cron(api)
        fake_clock.advance(timedelta(minutes=30))
        reconciler.reconcile("default", "demo")
        assert api.events(reason="TooManyMissedTimes") == []

    def test_last_schedule_time_resumes(self, api, fake_clock, reconciler):
        """Crash/fail-over recovery: lastScheduleTime persisted in status is
        the recovery point (SURVEY.md §5 failure detection)."""
        make_cron(api)
        fake_clock.advance(timedelta(minutes=2))
        reconciler.reconcile("default", "demo")
        # "restart": new reconciler instance sees persisted status
        fresh = CronReconciler(api)
        fake_clock.advance(timedelta(minutes=2))
        fresh.reconcile("default", "demo")
        assert len(list_jobs(api)) == 2


class TestClockJumpSafety:
    """Satellite (PR 20): a backwards wall-clock step (NTP step, VM
    migration) must not double-fire a tick this process already fired —
    even when the status write that would prove the fire was also lost.
    The monotonic-anchored last-fire guard detects the jump, suppresses
    the re-fire, and counts it exactly once per jump."""

    def test_backward_jump_suppresses_refire(self, api, fake_clock):
        from cron_operator_tpu.runtime.manager import Metrics
        metrics = Metrics()
        r = CronReconciler(api, metrics=metrics)
        make_cron(api)
        fake_clock.advance(timedelta(minutes=2))
        r.reconcile("default", "demo")
        jobs = list_jobs(api)
        assert len(jobs) == 1

        # Kill both wall-clock breadcrumbs that normally prevent the
        # double fire: the created workload (AlreadyExists collision)
        # and lastScheduleTime (regressed, as if the status write was
        # lost in a fail-over).
        api.delete(JAX_AV, JAX_KIND, "default",
                   jobs[0]["metadata"]["name"])
        api.patch_status(API_VERSION, KIND_CRON, "default", "demo",
                         {"lastScheduleTime": "2026-01-01T00:00:00Z"})
        # The wall clock steps 30s backwards; monotonic time (real, in
        # this process) keeps running. The tick at T0+1min now looks
        # missed again.
        fake_clock.advance(-timedelta(seconds=30))
        r.reconcile("default", "demo")
        assert list_jobs(api) == []  # no second workload
        assert metrics.counters.get("cron_clock_jumps_total") == 1
        assert len(api.events(reason="ClockJump")) == 1

        # Counted once per jump, not once per reconcile.
        r.reconcile("default", "demo")
        assert metrics.counters.get("cron_clock_jumps_total") == 1

        # Once wall time catches back up past the fire, fresh ticks
        # fire normally — the guard never wedges the schedule.
        fake_clock.advance(timedelta(minutes=3))
        r.reconcile("default", "demo")
        assert len(list_jobs(api)) == 1

    def test_forward_catchup_is_not_a_jump(self, api, fake_clock):
        from cron_operator_tpu.runtime.manager import Metrics
        metrics = Metrics()
        r = CronReconciler(api, metrics=metrics)
        make_cron(api)
        fake_clock.advance(timedelta(minutes=2))
        r.reconcile("default", "demo")
        # Plain forward progress (even a big leap: the TooManyMissed
        # path) must not count as a clock jump.
        fake_clock.advance(timedelta(hours=3))
        r.reconcile("default", "demo")
        assert metrics.counters.get("cron_clock_jumps_total") is None
        assert len(list_jobs(api)) == 2


class TestMalformedStatus:
    def test_malformed_status_workload_skipped(self, api, fake_clock, reconciler):
        """A workload whose status fails conversion is skipped entirely —
        it neither blocks Forbid policy forever nor enters history
        (reference `continue` at cron_controller.go:139-143)."""
        make_cron(api, policy="Forbid")
        fake_clock.advance(timedelta(minutes=2))
        reconciler.reconcile("default", "demo")
        name = list_jobs(api)[0]["metadata"]["name"]
        # corrupt the status
        api.patch_status(JAX_AV, JAX_KIND, "default", name,
                         {"conditions": "garbage"})
        fake_clock.advance(timedelta(minutes=2))
        reconciler.reconcile("default", "demo")
        # the broken workload did not count as active → Forbid still fired
        assert len(list_jobs(api)) == 2
        cron = get_cron(api)
        names_in_status = {a["name"] for a in cron["status"].get("active", [])}
        assert name not in names_in_status
