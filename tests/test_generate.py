"""Autoregressive generation (workloads/generate.py): the KV-cache decode
path must be REDUNDANT with the training forward — same math, different
incrementality — so greedy decode is verified token-for-token against
re-running the full model on the growing sequence."""

import jax
import jax.numpy as jnp
import pytest

from cron_operator_tpu.models import GPT, GPTConfig
from cron_operator_tpu.workloads.generate import generate


@pytest.fixture(scope="module")
def cpu0():
    return jax.devices("cpu")[0]


def _tiny(**over):
    # f32 + XLA attention: the equivalence check needs the cached and
    # full paths to differ only by float-op order, not dtype rounding.
    defaults = dict(
        vocab_size=128, hidden_size=32, num_layers=2, num_heads=2,
        mlp_dim=64, max_len=32, dtype=jnp.float32, attention_impl="xla",
    )
    defaults.update(over)
    return GPTConfig(**defaults)


def _init(cfg, batch=2):
    model = GPT(cfg)
    prompt = jax.random.randint(
        jax.random.PRNGKey(1), (batch, 4), 0, cfg.vocab_size,
        dtype=jnp.int32,
    )
    params = model.init(jax.random.PRNGKey(0), prompt)["params"]
    return model, params, prompt


class TestGreedyEquivalence:
    def test_matches_full_forward_rerun(self, cpu0):
        with jax.default_device(cpu0):
            cfg = _tiny()
            model, params, prompt = _init(cfg)
            out = generate(cfg, params, prompt, max_new_tokens=6)
            assert out.shape == (2, 10)
            assert (out[:, :4] == prompt).all()

            # Oracle: no cache — re-run the whole sequence every step.
            seq = prompt
            for _ in range(6):
                logits, _ = model.apply({"params": params}, seq)
                nxt = jnp.argmax(logits[:, -1], axis=-1)[:, None]
                seq = jnp.concatenate([seq, nxt.astype(seq.dtype)], axis=1)
            assert (out == seq).all(), (
                "cached decode diverged from the full forward"
            )

    def test_single_token_prompt(self, cpu0):
        with jax.default_device(cpu0):
            cfg = _tiny()
            _, params, prompt = _init(cfg)
            out = generate(cfg, params, prompt[:, :1], max_new_tokens=3)
            assert out.shape == (2, 4)


class TestSampling:
    def test_deterministic_per_key_and_varies_across_keys(self, cpu0):
        with jax.default_device(cpu0):
            cfg = _tiny()
            _, params, prompt = _init(cfg)
            a = generate(cfg, params, prompt, 8, temperature=5.0,
                         rng=jax.random.PRNGKey(7))
            b = generate(cfg, params, prompt, 8, temperature=5.0,
                         rng=jax.random.PRNGKey(7))
            c = generate(cfg, params, prompt, 8, temperature=5.0,
                         rng=jax.random.PRNGKey(8))
            assert (a == b).all()
            # temperature 5 over 128 logits: 8 identical draws across two
            # keys is vanishingly unlikely with an untrained model
            assert not (a[:, 4:] == c[:, 4:]).all()


class TestMoEDecode:
    def test_moe_greedy_matches_full_forward(self, cpu0):
        """Same oracle as the dense test, for MoE blocks. The config's
        capacity factor guarantees no token drops in EITHER path (decode
        always raises its own capacity; the full forward needs the config
        to), so routing divergence can't hide behind dropped tokens."""
        with jax.default_device(cpu0):
            cfg = _tiny(moe_every=1, num_experts=4,
                        moe_capacity_factor=4.0)
            model, params, prompt = _init(cfg)
            out = generate(cfg, params, prompt, max_new_tokens=4)
            seq = prompt
            for _ in range(4):
                logits, _ = model.apply({"params": params}, seq)
                nxt = jnp.argmax(logits[:, -1], axis=-1)[:, None]
                seq = jnp.concatenate([seq, nxt.astype(seq.dtype)], axis=1)
            assert (out == seq).all(), (
                "cached MoE decode diverged from the full forward"
            )


class TestValidation:
    def test_rejects_overflow_and_bad_args(self, cpu0):
        with jax.default_device(cpu0):
            cfg = _tiny()
            _, params, prompt = _init(cfg)
            with pytest.raises(ValueError, match="exceeds"):
                generate(cfg, params, prompt, max_new_tokens=29)
            with pytest.raises(ValueError, match="empty prompt"):
                generate(cfg, params, prompt[:, :0], 1)
            with pytest.raises(ValueError, match="needs an rng"):
                generate(cfg, params, prompt, 1, temperature=1.0)
            with pytest.raises(ValueError, match=">= 0"):
                generate(cfg, params, prompt, 1, temperature=-1.0)
            with pytest.raises(ValueError, match="must be >= 1"):
                generate(cfg, params, prompt, 0)


class TestModernAttentionDecode:
    """GQA + RoPE through the same greedy oracle: the cached decode path
    (grouped einsum over a kv_heads-sized cache, per-position rotations)
    must reproduce the full training forward exactly."""

    def test_gqa_rope_greedy_matches_full_forward(self, cpu0):
        with jax.default_device(cpu0):
            cfg = _tiny(num_heads=4, num_kv_heads=2, rope=True)
            model, params, prompt = _init(cfg)
            assert "pos_emb" not in params  # RoPE replaces the table
            out = generate(cfg, params, prompt, max_new_tokens=5)
            seq = prompt
            for _ in range(5):
                logits, _ = model.apply({"params": params}, seq)
                nxt = jnp.argmax(logits[:, -1], axis=-1)[:, None]
                seq = jnp.concatenate([seq, nxt.astype(seq.dtype)], axis=1)
            assert (out == seq).all(), (
                "GQA/RoPE cached decode diverged from the full forward"
            )

    def test_gqa_cache_is_kv_heads_sized(self, cpu0):
        """The whole point of GQA at serving time: the cache stores
        kv_heads, not num_heads."""
        from cron_operator_tpu.models.gpt import GPT as _GPT

        with jax.default_device(cpu0):
            from dataclasses import replace as _replace

            cfg = _tiny(num_heads=4, num_kv_heads=2)
            _, params, prompt = _init(cfg)
            decode = _GPT(_replace(cfg, return_hidden=False), decode=True)
            _, mut = decode.apply(
                {"params": params}, prompt[:, :1], mutable=["cache"]
            )
            k = mut["cache"]["layer_0"]["k"]
            assert k.shape == (2, cfg.max_len, 2, cfg.hidden_size // 4)

    def test_invalid_kv_heads_rejected(self, cpu0):
        with jax.default_device(cpu0):
            cfg = _tiny(num_heads=4, num_kv_heads=3)
            model = GPT(cfg)
            with pytest.raises(ValueError, match="positive divisor"):
                model.init(
                    jax.random.PRNGKey(0),
                    jnp.zeros((1, 4), jnp.int32),
                )


class TestCompiledCacheBound:
    def test_lru_cap_bounds_distinct_keys(self, cpu0, monkeypatch):
        """Many distinct (config, max_new) keys must not grow _COMPILED
        without bound (ADVICE r4: a long-lived serving operator fed
        varying max_new retains every jitted fn forever)."""
        import cron_operator_tpu.workloads.generate as gen

        monkeypatch.setattr(gen, "_COMPILED", type(gen._COMPILED)())
        built = []

        def fake_build(config, max_new, greedy):
            built.append(max_new)
            return lambda *a: jnp.zeros((1, 1), jnp.int32)

        monkeypatch.setattr(gen, "_build", fake_build)
        cfg = _tiny()
        prompt = jnp.zeros((1, 2), jnp.int32)
        for max_new in range(1, gen._COMPILED_CAP + 9):
            gen.generate(cfg, {}, prompt, max_new)
        assert len(gen._COMPILED) == gen._COMPILED_CAP

        # LRU, not FIFO: re-touching a resident key keeps it resident.
        survivor = max(built) - 1
        gen.generate(cfg, {}, prompt, survivor)  # touch → most-recent
        n_built = len(built)
        gen.generate(cfg, {}, prompt, 1)  # evicts the true LRU entry
        gen.generate(cfg, {}, prompt, survivor)  # still cached: no build
        assert len(built) == n_built + 1
        assert len(gen._COMPILED) == gen._COMPILED_CAP
