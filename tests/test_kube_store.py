"""Embedded control-plane (APIServer) tests: CRUD, optimistic concurrency,
label selection, watches, owner-reference GC cascade, events."""

import pytest

from cron_operator_tpu.runtime.kube import (
    AlreadyExistsError,
    APIServer,
    ConflictError,
    InvalidError,
    NotFoundError,
)


def job(name, ns="default", labels=None, owners=None):
    obj = {
        "apiVersion": "kubeflow.org/v1",
        "kind": "JAXJob",
        "metadata": {"name": name, "namespace": ns},
    }
    if labels:
        obj["metadata"]["labels"] = labels
    if owners:
        obj["metadata"]["ownerReferences"] = owners
    return obj


class TestCrud:
    def test_create_sets_metadata(self, api):
        created = api.create(job("a"))
        meta = created["metadata"]
        assert meta["uid"]
        assert meta["resourceVersion"]
        assert meta["creationTimestamp"]

    def test_create_requires_gvk(self, api):
        with pytest.raises(InvalidError):
            api.create({"metadata": {"name": "a"}})

    def test_duplicate_create(self, api):
        api.create(job("a"))
        with pytest.raises(AlreadyExistsError):
            api.create(job("a"))

    def test_generate_name(self, api):
        created = api.create(
            {
                "apiVersion": "v1",
                "kind": "Pod",
                "metadata": {"generateName": "worker-", "namespace": "default"},
            }
        )
        assert created["metadata"]["name"].startswith("worker-")

    def test_get_not_found(self, api):
        with pytest.raises(NotFoundError):
            api.get("kubeflow.org/v1", "JAXJob", "default", "nope")

    def test_returns_copies(self, api):
        api.create(job("a"))
        got = api.get("kubeflow.org/v1", "JAXJob", "default", "a")
        got["metadata"]["labels"] = {"mutated": "yes"}
        again = api.get("kubeflow.org/v1", "JAXJob", "default", "a")
        assert "labels" not in again["metadata"]

    def test_update_conflict(self, api):
        created = api.create(job("a"))
        stale = dict(created)
        api.update(created)  # bumps rv
        with pytest.raises(ConflictError):
            api.update(stale)

    def test_list_label_selector(self, api):
        api.create(job("a", labels={"kubedl.io/cron-name": "c1"}))
        api.create(job("b", labels={"kubedl.io/cron-name": "c2"}))
        api.create(job("c"))
        out = api.list(
            "kubeflow.org/v1",
            "JAXJob",
            namespace="default",
            label_selector={"kubedl.io/cron-name": "c1"},
        )
        assert [o["metadata"]["name"] for o in out] == ["a"]

    def test_list_namespace_scoping(self, api):
        api.create(job("a", ns="ns1"))
        api.create(job("a", ns="ns2"))
        assert len(api.list("kubeflow.org/v1", "JAXJob")) == 2
        assert len(api.list("kubeflow.org/v1", "JAXJob", namespace="ns1")) == 1


class TestStatusPatch:
    def test_patch_and_noop_shortcircuit(self, api):
        created = api.create(job("a"))
        rv0 = created["metadata"]["resourceVersion"]
        patched = api.patch_status(
            "kubeflow.org/v1", "JAXJob", "default", "a",
            {"conditions": [{"type": "Running", "status": "True"}]},
        )
        rv1 = patched["metadata"]["resourceVersion"]
        assert rv1 != rv0
        # semantically equal patch → no rv bump
        again = api.patch_status(
            "kubeflow.org/v1", "JAXJob", "default", "a",
            {"conditions": [{"type": "Running", "status": "True"}]},
        )
        assert again["metadata"]["resourceVersion"] == rv1


class TestGarbageCollection:
    def test_cascade_delete(self, api):
        owner = api.create(job("parent"))
        uid = owner["metadata"]["uid"]
        api.create(
            {
                "apiVersion": "v1",
                "kind": "Pod",
                "metadata": {
                    "name": "child",
                    "namespace": "default",
                    "ownerReferences": [
                        {"kind": "JAXJob", "uid": uid, "controller": True}
                    ],
                },
            }
        )
        api.delete("kubeflow.org/v1", "JAXJob", "default", "parent")
        assert api.try_get("v1", "Pod", "default", "child") is None

    def test_orphan_propagation(self, api):
        owner = api.create(job("parent"))
        uid = owner["metadata"]["uid"]
        api.create(
            {
                "apiVersion": "v1",
                "kind": "Pod",
                "metadata": {
                    "name": "child",
                    "namespace": "default",
                    "ownerReferences": [{"kind": "JAXJob", "uid": uid}],
                },
            }
        )
        api.delete(
            "kubeflow.org/v1", "JAXJob", "default", "parent", propagation="Orphan"
        )
        assert api.try_get("v1", "Pod", "default", "child") is not None


class TestWatchAndEvents:
    def test_watch_stream(self, api):
        seen = []
        api.add_watcher(
            lambda ev: seen.append((ev.type, ev.object["metadata"]["name"])))
        api.create(job("a"))
        api.patch_status("kubeflow.org/v1", "JAXJob", "default", "a", {"x": 1})
        api.delete("kubeflow.org/v1", "JAXJob", "default", "a")
        assert api.flush()  # delivery is async; barrier before asserting
        assert seen == [("ADDED", "a"), ("MODIFIED", "a"), ("DELETED", "a")]

    def test_slow_subscriber_does_not_block_writes(self, api):
        """VERDICT r3 #9: a subscriber that does I/O must not stall API
        writes — fan-out happens on the dispatcher thread, publish is an
        append under the lock."""
        import time

        release = __import__("threading").Event()
        seen = []

        def slow(ev):
            release.wait(5.0)
            seen.append(ev.type)

        api.add_watcher(slow)
        t0 = time.monotonic()
        api.create(job("a"))
        api.create(job("b"))  # second write while the first delivery blocks
        write_elapsed = time.monotonic() - t0
        assert write_elapsed < 1.0, (
            f"writes blocked {write_elapsed:.2f}s behind a slow subscriber"
        )
        release.set()
        assert api.flush()
        assert seen == ["ADDED", "ADDED"]

    def test_watcher_exception_does_not_poison_delivery(self, api):
        seen = []

        def bad(ev):
            raise RuntimeError("boom")

        api.add_watcher(bad)
        api.add_watcher(lambda ev: seen.append(ev.object["metadata"]["name"]))
        api.create(job("a"))
        assert api.flush()
        assert seen == ["a"]

    def test_events(self, api):
        cron = {"apiVersion": "apps.kubedl.io/v1alpha1", "kind": "Cron",
                "metadata": {"name": "c", "namespace": "default"}}
        api.record_event(cron, "Warning", "FailedCreate", "boom")
        evs = api.events(reason="FailedCreate")
        assert len(evs) == 1
        assert evs[0].involved_name == "c"
        assert evs[0].type == "Warning"

    def test_record_event_also_creates_v1_event_object(self, api):
        """Events must be listable as corev1 Event objects (what the REST
        facade and `describe` read), not only via the in-process side
        list."""
        cron = {"apiVersion": "apps.kubedl.io/v1alpha1", "kind": "Cron",
                "metadata": {"name": "c", "namespace": "ns9"}}
        api.record_event(cron, "Warning", "FailedTPUAdmission", "bad topo")
        objs = api.list("v1", "Event", namespace="ns9")
        assert len(objs) == 1
        ev = objs[0]
        assert ev["reason"] == "FailedTPUAdmission"
        assert ev["involvedObject"]["kind"] == "Cron"
        assert ev["involvedObject"]["name"] == "c"
        assert ev["type"] == "Warning"
        # the side list keeps working for test assertions
        assert api.events(reason="FailedTPUAdmission")
