"""The five BASELINE.md acceptance configs, deterministic.

Round-4 rewrite (VERDICT r3 #3, asked since r1): the five configs used to
drive the live manager with ``@every``-second schedules and wall-clock
polling — green but load-sensitive. They now run the way the reference's
own controller tests do (``cron_controller_test.go:90-129``: backdated
``LastScheduleTime``, no sleeps): a FakeClock-backed APIServer, direct
``reconciler.reconcile()`` calls, and workload terminal states hand-set
through the status subresource (the reference hand-crafts JobStatus the
same way — SURVEY.md §4 "jobs are created and listed but never run").

The live-stack versions (real Manager worker pools + LocalExecutor
threads + actual training) live in ``test_acceptance_smoke.py`` — one
wall-clock smoke per concurrency policy.
"""

from datetime import timedelta

import pytest

from cron_operator_tpu.backends.tpu import (
    NODESEL_ACCELERATOR,
    NODESEL_TOPOLOGY,
)
from cron_operator_tpu.controller import CronReconciler
from cron_operator_tpu.runtime.manager import Metrics

JAX = "kubeflow.org/v1"
CRON_API = "apps.kubedl.io/v1alpha1"


def _cron(name, schedule, workload, policy="Allow", history=100, **spec_extra):
    spec = {
        "schedule": schedule,
        "concurrencyPolicy": policy,
        "historyLimit": history,
        "template": {"workload": workload},
    }
    spec.update(spec_extra)
    return {
        "apiVersion": CRON_API,
        "kind": "Cron",
        "metadata": {"name": name, "namespace": "default"},
        "spec": spec,
    }


def _workload(kind="JAXJob", annotations=None, replicas=1):
    return {
        "apiVersion": JAX,
        "kind": kind,
        "metadata": {"annotations": dict(annotations or {})},
        "spec": {"replicaSpecs": {"Worker": {"replicas": replicas}}},
    }


@pytest.fixture
def rig(api, fake_clock):
    """(api, reconciler, clock, metrics) on deterministic time."""
    metrics = Metrics()
    rec = CronReconciler(api, metrics=metrics)
    return api, rec, fake_clock, metrics


def _tick(rig, name, seconds=61):
    """Advance virtual time past the next activation and reconcile."""
    api, rec, clock, _ = rig
    clock.advance(timedelta(seconds=seconds))
    return rec.reconcile("default", name)


def _jobs(api, kind="JAXJob"):
    return api.list(JAX, kind, namespace="default")


def _active(api, kind="JAXJob"):
    out = []
    for j in _jobs(api, kind):
        conds = [c["type"] for c in (j.get("status") or {}).get("conditions") or []]
        if "Succeeded" not in conds and "Failed" not in conds:
            out.append(j)
    return out


def _finish(api, name, kind="JAXJob", cond="Succeeded"):
    """Hand-set a terminal JobStatus (reference test technique)."""
    api.patch_status(
        JAX, kind, "default", name,
        {"conditions": [
            {"type": "Running", "status": "True"},
            {"type": cond, "status": "True"},
        ]},
    )


class TestConfig1TFJobForbid:
    """Single-replica TFJob (CPU), Forbid: a tick is skipped while a run
    is active — never two overlapping workloads."""

    def test_forbid_prevents_overlap(self, rig):
        api, rec, clock, metrics = rig
        api.create(_cron("tf-mnist", "@every 60s", _workload("TFJob"),
                         policy="Forbid"))

        _tick(rig, "tf-mnist")
        assert len(_jobs(api, "TFJob")) == 1

        # Next two ticks arrive while the first run is still active.
        _tick(rig, "tf-mnist")
        _tick(rig, "tf-mnist")
        assert len(_jobs(api, "TFJob")) == 1, "Forbid must skip, not stack"
        assert metrics.get('cron_ticks_skipped_total{policy="Forbid"}') >= 1

        # Run finishes → the following tick fires again.
        _finish(api, _jobs(api, "TFJob")[0]["metadata"]["name"], "TFJob")
        _tick(rig, "tf-mnist")
        assert len(_jobs(api, "TFJob")) == 2
        assert len(_active(api, "TFJob")) == 1
        assert metrics.get("cron_ticks_fired_total") == 2


class TestConfig2JaxMnistV5e1:
    """Single-host JAXJob on v5e-1: TPU admission injects slice metadata
    on the object the reconciler POSTs (executor-side training is covered
    by the smoke tier + test_local_executor)."""

    def test_admission_injects_topology(self, rig):
        api, rec, clock, _ = rig
        api.create(_cron(
            "jax-mnist", "@every 60s",
            _workload("JAXJob", {
                "tpu.kubedl.io/accelerator": "v5e-1",
                "tpu.kubedl.io/entrypoint": "mnist",
                "tpu.kubedl.io/param.steps": "2",
            }),
            policy="Forbid",
        ))
        _tick(rig, "jax-mnist")
        jobs = _jobs(api)
        assert len(jobs) == 1
        worker = jobs[0]["spec"]["replicaSpecs"]["Worker"]
        sel = worker["template"]["spec"]["nodeSelector"]
        assert sel[NODESEL_ACCELERATOR] == "tpu-v5-lite-podslice"
        assert sel[NODESEL_TOPOLOGY] == "1x1"
        assert worker["replicas"] == 1  # single host
        res = worker["template"]["spec"]["containers"][0]["resources"]
        assert res["limits"]["google.com/tpu"] == "1"
        # Owner ref + label wire the job back to its cron.
        meta = jobs[0]["metadata"]
        assert meta["labels"]["kubedl.io/cron-name"] == "jax-mnist"
        assert meta["ownerReferences"][0]["kind"] == "Cron"

    def test_invalid_topology_fails_admission_not_cron(self, rig):
        api, rec, clock, _ = rig
        api.create(_cron(
            "jax-bad", "@every 60s",
            _workload("JAXJob", {"tpu.kubedl.io/accelerator": "v99-0"}),
            policy="Forbid",
        ))
        _tick(rig, "jax-bad")
        assert len(_jobs(api)) == 0
        assert api.events(reason="FailedTPUAdmission")


class TestConfig3ResnetV5e16Replace:
    """Multi-host v5e-16 (4 hosts × 4 chips): replicas = hosts; Replace
    deletes the previous generation before launching the next."""

    def test_gang_and_replace(self, rig):
        api, rec, clock, _ = rig
        api.create(_cron(
            "resnet", "@every 60s",
            _workload("JAXJob", {
                "tpu.kubedl.io/accelerator": "tpu-v5-lite-podslice",
                "tpu.kubedl.io/topology": "4x4",
            }, replicas=4),
            policy="Replace",
        ))
        _tick(rig, "resnet")
        gen1 = _jobs(api)
        assert len(gen1) == 1
        assert gen1[0]["spec"]["replicaSpecs"]["Worker"]["replicas"] == 4
        ann = gen1[0]["metadata"]["annotations"]
        assert ann["tpu.kubedl.io/gang-size"] == "4"

        # Second tick with gen1 still active: Replace must swap, not stack.
        _tick(rig, "resnet")
        gen2 = _jobs(api)
        assert len(gen2) == 1, "Replace must never stack runs"
        assert gen2[0]["metadata"]["name"] != gen1[0]["metadata"]["name"]
        assert api.try_get(
            JAX, "JAXJob", "default", gen1[0]["metadata"]["name"]
        ) is None, "previous generation must be deleted"


class TestConfig4AllowHistoryLimit:
    """Allow stacks overlapping runs; historyLimit=5 garbage-collects the
    oldest finished workloads (their history entries go with them)."""

    def test_overlap(self, rig):
        api, rec, clock, metrics = rig
        api.create(_cron("allow3", "@every 60s", _workload("JAXJob"),
                         policy="Allow", history=5))
        for _ in range(3):
            _tick(rig, "allow3")
        assert len(_active(api)) == 3, "Allow must stack overlapping runs"
        assert metrics.get("cron_ticks_fired_total") == 3

    def test_history_gc(self, rig):
        api, rec, clock, _ = rig
        api.create(_cron("gc5", "@every 60s", _workload("JAXJob"),
                         policy="Allow", history=5))
        # Eight completed generations, distinct creation times.
        for _ in range(8):
            _tick(rig, "gc5")
            for j in _active(api):
                _finish(api, j["metadata"]["name"])
        # One more reconcile syncs history and GCs beyond the limit.
        (api_, rec_, clock_, _m) = rig
        rec_.reconcile("default", "gc5")
        cron = api.get(CRON_API, "Cron", "default", "gc5")
        history = (cron.get("status") or {}).get("history") or []
        assert len(history) == 5
        assert len(_jobs(api)) == 5, "GC must delete beyond historyLimit"
        assert all(h["status"] == "Succeeded" for h in history)


class TestConfig5SuspendDeadlinePreemption:
    """Suspend gates ticks; a preempted (Restarting) job counts as active
    so Forbid keeps skipping; a passed deadline stops scheduling with a
    Deadline event."""

    def test_suspend_then_resume(self, rig):
        api, rec, clock, _ = rig
        api.create(_cron("bert", "@every 60s", _workload("JAXJob"),
                         policy="Forbid", suspend=True))
        _tick(rig, "bert")
        _tick(rig, "bert")
        assert len(_jobs(api)) == 0, "suspended cron must not fire"

        cron = api.get(CRON_API, "Cron", "default", "bert")
        cron["spec"]["suspend"] = False
        api.update(cron)
        _tick(rig, "bert")
        assert len(_jobs(api)) == 1, "unsuspended cron must fire"

    def test_restarting_counts_as_active(self, rig):
        """Slice preemption surfaces as Restarting (not terminal) — the
        reconciler must treat it as active: Forbid skips, Replace would
        delete. Terminal Failed then frees the next tick."""
        api, rec, clock, _ = rig
        api.create(_cron("bert-pre", "@every 60s", _workload("JAXJob"),
                         policy="Forbid"))
        _tick(rig, "bert-pre")
        name = _jobs(api)[0]["metadata"]["name"]
        api.patch_status(
            JAX, "JAXJob", "default", name,
            {"conditions": [
                {"type": "Running", "status": "True"},
                {"type": "Restarting", "status": "True"},
            ]},
        )
        _tick(rig, "bert-pre")
        assert len(_jobs(api)) == 1, "Restarting job is active; Forbid skips"

        _finish(api, name, cond="Failed")
        _tick(rig, "bert-pre")
        assert len(_jobs(api)) == 2, "terminal Failed frees the next tick"

    def test_deadline_stops_scheduling(self, rig):
        api, rec, clock, _ = rig
        api.create(_cron("bert-dead", "@every 60s", _workload("JAXJob"),
                         policy="Forbid", deadline="2020-01-01T00:00:00Z"))
        _tick(rig, "bert-dead")
        _tick(rig, "bert-dead")
        assert len(_jobs(api)) == 0
        assert api.events(reason="Deadline"), "Deadline event must fire"
