"""The five BASELINE.md acceptance configs, end-to-end on the live stack
(RealClock manager + executor; `@every` schedules keep wall time in
seconds). This closes the e2e gap the reference left open — its e2e never
applies a Cron CR (``/root/reference/test/e2e/e2e_test.go:281-289`` TODO);
here every config drives Cron → reconcile → workload → (real or simulated)
execution → status/history.
"""

import time

import pytest

from cron_operator_tpu.api.scheme import GVK_CRON, default_scheme
from cron_operator_tpu.backends.local import LocalExecutor
from cron_operator_tpu.backends.tpu import NODESEL_ACCELERATOR, NODESEL_TOPOLOGY
from cron_operator_tpu.controller import CronReconciler
from cron_operator_tpu.runtime import APIServer, Manager

JAX = "kubeflow.org/v1"


def _cron(name, schedule, workload, policy="Allow", history=100, **spec_extra):
    spec = {
        "schedule": schedule,
        "concurrencyPolicy": policy,
        "historyLimit": history,
        "template": {"workload": workload},
    }
    spec.update(spec_extra)
    return {
        "apiVersion": "apps.kubedl.io/v1alpha1",
        "kind": "Cron",
        "metadata": {"name": name, "namespace": "default"},
        "spec": spec,
    }


def _workload(kind="JAXJob", annotations=None, replicas=1):
    return {
        "apiVersion": JAX,
        "kind": kind,
        "metadata": {"annotations": dict(annotations or {})},
        "spec": {"replicaSpecs": {"Worker": {"replicas": replicas}}},
    }


@pytest.fixture
def stack():
    api = APIServer()
    mgr = Manager(api, max_concurrent_reconciles=10)
    rec = CronReconciler(api, metrics=mgr.metrics)
    mgr.add_controller(
        "cron", rec.reconcile, for_gvk=GVK_CRON,
        owns=default_scheme().workload_kinds(),
    )
    ex = LocalExecutor(api)
    ex.start()
    mgr.start()
    yield api, mgr, ex
    mgr.stop()
    ex.stop()


def _jobs(api, kind="JAXJob"):
    return api.list(JAX, kind, namespace="default")


def _active(api, kind="JAXJob"):
    out = []
    for j in _jobs(api, kind):
        conds = [c["type"] for c in (j.get("status") or {}).get("conditions") or []]
        if "Succeeded" not in conds and "Failed" not in conds:
            out.append(j)
    return out


class TestConfig1TFJobForbid:
    """Single-replica TFJob (CPU), Forbid: ticks are skipped while a run is
    active — never two overlapping workloads."""

    def test_forbid_prevents_overlap(self, stack):
        api, _, _ = stack
        api.create(_cron(
            "tf-mnist", "@every 1s",
            _workload("TFJob", {"tpu.kubedl.io/simulate-duration": "2500ms"}),
            policy="Forbid",
        ))
        max_active = 0
        deadline = time.time() + 6.0
        while time.time() < deadline:
            max_active = max(max_active, len(_active(api, "TFJob")))
            time.sleep(0.1)
        assert max_active == 1
        total = len(_jobs(api, "TFJob"))
        assert 1 <= total <= 3  # ~2.5s each over ~6s, ticks skipped between
        # Domain metrics: fired ticks and Forbid skips were counted.
        _, mgr, _ = stack
        snap = mgr.metrics.snapshot()
        assert snap.get("cron_ticks_fired_total", 0) == total
        assert snap.get('cron_ticks_skipped_total{policy="Forbid"}', 0) >= 1


class TestConfig2JaxMnistV5e1:
    """Single-host JAXJob MNIST on v5e-1: real training (CPU devices stand
    in for the chip), TPU admission injects slice metadata."""

    def test_trains_and_injects_topology(self, stack):
        api, _, ex = stack
        api.create(_cron(
            "jax-mnist", "@every 1s",
            _workload("JAXJob", {
                "tpu.kubedl.io/accelerator": "v5e-1",
                "tpu.kubedl.io/entrypoint": "mnist",
                "tpu.kubedl.io/param.steps": "2",
                "tpu.kubedl.io/param.batch_size": "16",
                "tpu.kubedl.io/param.platform": "cpu",
            }),
            policy="Forbid",
        ))
        deadline = time.time() + 60.0
        done = None
        while time.time() < deadline and done is None:
            for j in _jobs(api):
                st = j.get("status") or {}
                if (st.get("trainingProgress") or {}).get("steps_done") == 2:
                    done = j
            time.sleep(0.2)
        assert done is not None, "mnist job never finished training"
        worker = done["spec"]["replicaSpecs"]["Worker"]
        sel = worker["template"]["spec"]["nodeSelector"]
        assert sel[NODESEL_ACCELERATOR] == "tpu-v5-lite-podslice"
        assert sel[NODESEL_TOPOLOGY] == "1x1"
        assert worker["replicas"] == 1  # single host
        res = worker["template"]["spec"]["containers"][0]["resources"]
        assert res["limits"]["google.com/tpu"] == "1"


class TestConfig3ResnetV5e16Replace:
    """Multi-host v5e-16 (4 hosts × 4 chips): the gang is 4 pods; Replace
    deletes the whole previous pod group before launching the next run."""

    def test_gang_and_replace(self, stack):
        api, _, _ = stack
        api.create(_cron(
            "resnet", "@every 2s",
            _workload("JAXJob", {
                "tpu.kubedl.io/accelerator": "tpu-v5-lite-podslice",
                "tpu.kubedl.io/topology": "4x4",
                "tpu.kubedl.io/simulate-duration": "30s",
            }, replicas=4),
            policy="Replace",
        ))
        deadline = time.time() + 9.0
        saw_pods = 0
        while time.time() < deadline:
            pods = api.list("v1", "Pod", namespace="default")
            saw_pods = max(saw_pods, len(pods))
            assert len(_active(api)) <= 1, "Replace must never stack runs"
            time.sleep(0.2)
        # one gang at a time: 4 host pods, never 8
        assert saw_pods == 4
        # replacement happened: the job name (tick timestamp) moved on
        names = {j["metadata"]["name"] for j in _jobs(api)}
        assert len(names) == 1  # exactly one generation alive
        gang = (_jobs(api)[0]["metadata"]["annotations"] or {})
        assert gang.get("tpu.kubedl.io/gang-size") == "4"


class TestConfig4AllowHistoryLimit:
    """Allow concurrency stacks overlapping runs; historyLimit=5 garbage
    collects the oldest finished workloads."""

    def test_overlap_and_history_gc(self, stack):
        api, _, _ = stack
        api.create(_cron(
            "allow3", "@every 1s",
            _workload("JAXJob", {"tpu.kubedl.io/simulate-duration": "2800ms"}),
            policy="Allow", history=5,
        ))
        max_active = 0
        deadline = time.time() + 12.0
        while time.time() < deadline:
            max_active = max(max_active, len(_active(api)))
            time.sleep(0.1)
        assert max_active >= 3, f"expected 3-way overlap, saw {max_active}"
        # GC: retained finished jobs never exceed the limit by more than the
        # one-reconcile-lag the reference design allows.
        cron = api.get("apps.kubedl.io/v1alpha1", "Cron", "default", "allow3")
        history = (cron.get("status") or {}).get("history") or []
        assert len(history) <= 5


class TestConfig5SuspendDeadlinePreemption:
    """Suspend gates ticks; preemption of a multi-host slice kills the gang
    and (with restart-on-preemption) re-runs the job; a passed deadline
    stops scheduling with a Deadline event."""

    def test_suspend_then_resume(self, stack):
        api, _, _ = stack
        api.create(_cron(
            "bert", "@every 1s",
            _workload("JAXJob", {"tpu.kubedl.io/simulate-duration": "200ms"}),
            policy="Forbid", suspend=True,
        ))
        time.sleep(2.5)
        assert len(_jobs(api)) == 0, "suspended cron must not fire"
        cron = api.get("apps.kubedl.io/v1alpha1", "Cron", "default", "bert")
        cron["spec"]["suspend"] = False
        api.update(cron)
        deadline = time.time() + 8.0
        while time.time() < deadline and not _jobs(api):
            time.sleep(0.1)
        assert _jobs(api), "unsuspended cron must fire"

    def test_preemption_restart(self, stack):
        api, _, ex = stack
        api.create(_cron(
            "bert-pre", "@every 1s",
            _workload("JAXJob", {
                "tpu.kubedl.io/accelerator": "v5e-16",
                "tpu.kubedl.io/simulate-duration": "20s",
                "tpu.kubedl.io/restart-on-preemption": "true",
            }),
            policy="Forbid",
        ))
        deadline = time.time() + 8.0
        job = None
        while time.time() < deadline and job is None:
            running = [
                j for j in _jobs(api)
                if any(c["type"] == "Running"
                       for c in (j.get("status") or {}).get("conditions") or [])
            ]
            job = running[0] if running else None
            time.sleep(0.1)
        assert job is not None
        name = job["metadata"]["name"]
        assert len(api.list("v1", "Pod", namespace="default")) == 4

        ex.preempt("default", name)
        deadline = time.time() + 8.0
        restarted = False
        while time.time() < deadline and not restarted:
            j = api.try_get(JAX, "JAXJob", "default", name)
            conds = [c["type"] for c in (j.get("status") or {}).get("conditions") or []]
            restarted = "Restarting" in conds and conds.count("Running") >= 2
            time.sleep(0.1)
        assert restarted, "preempted job must go Restarting and re-run"

    def test_deadline_stops_scheduling(self, stack):
        api, _, _ = stack
        api.create(_cron(
            "bert-dead", "@every 1s",
            _workload("JAXJob", {"tpu.kubedl.io/simulate-duration": "100ms"}),
            policy="Forbid", deadline="2020-01-01T00:00:00Z",
        ))
        time.sleep(2.5)
        assert len(_jobs(api)) == 0
        assert api.events(reason="Deadline"), "Deadline event must fire"
