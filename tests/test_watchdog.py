"""Step-progress hang watchdog (runtime/watchdog.py).

The verdict logic in isolation, on injected clocks (no sleeps): budget
derivation from the EMA of observed step times, the floor for
bursty-but-fast runs, the startup grace for compile/restore, and the
no-false-positive guarantee for slow-but-progressing runs — the gray
failure this exists for is silence, not slowness.
"""

import unittest

from cron_operator_tpu.runtime.watchdog import (
    DEFAULT_STARTUP_GRACE_FLOORS,
    StepWatchdog,
)


class TestStepWatchdog(unittest.TestCase):
    def _wd(self, **kw):
        kw.setdefault("floor_s", 2.0)
        kw.setdefault("multiplier", 8.0)
        return StepWatchdog(**kw)

    def test_unarmed_never_stale(self):
        wd = self._wd()
        self.assertFalse(wd.stale(now=1e9))
        self.assertEqual(wd.staleness_s(now=1e9), 0.0)

    def test_startup_grace_covers_compile_then_floor_applies(self):
        wd = self._wd()
        wd.start(now=0.0)
        # Pre-first-beat budget is the startup grace (compile/restore),
        # not the step floor: 10s of silent compile is healthy...
        self.assertEqual(
            wd.budget_s(), DEFAULT_STARTUP_GRACE_FLOORS * 2.0)
        self.assertFalse(wd.stale(now=10.0))
        # ...but a run that NEVER reaches step 1 is still detectable.
        self.assertTrue(wd.stale(now=17.0))

    def test_first_interval_excluded_from_ema(self):
        wd = self._wd()
        wd.start(now=0.0)
        wd.beat(now=12.0)  # step 1 after a 12s compile
        self.assertIsNone(wd.ema_step_s)  # compile is not a step time
        wd.beat(now=12.5)
        self.assertAlmostEqual(wd.ema_step_s, 0.5)

    def test_budget_is_multiplier_times_ema_with_floor(self):
        wd = self._wd(floor_s=1.0, multiplier=8.0)
        wd.start(now=0.0)
        wd.beat(now=1.0)
        for i in range(2, 12):  # steady 2s steps
            wd.beat(now=1.0 + (i - 1) * 2.0)
        self.assertAlmostEqual(wd.ema_step_s, 2.0)
        self.assertAlmostEqual(wd.budget_s(), 16.0)
        # Fast steps: the floor keeps bursty runs from flapping.
        fast = self._wd(floor_s=30.0, multiplier=8.0)
        fast.start(now=0.0)
        for i in range(1, 20):
            fast.beat(now=i * 0.05)
        self.assertEqual(fast.budget_s(), 30.0)

    def test_slow_but_progressing_run_never_trips(self):
        # Steps take 5s each — slower than the 2s floor, but every beat
        # lands. The first real step rides the startup grace; once the
        # EMA exists the budget (8 x 5s = 40s) dwarfs the silence.
        wd = self._wd(floor_s=2.0)
        wd.start(now=0.0)
        t = 0.0
        for i in range(1, 30):
            t = i * 5.0
            self.assertFalse(wd.stale(now=t - 0.001))
            wd.beat(now=t)
        self.assertFalse(wd.stale(now=t + 4.9))

    def test_silence_past_budget_is_a_hang(self):
        wd = self._wd(floor_s=2.0, multiplier=8.0)
        wd.start(now=0.0)
        for i in range(1, 11):  # 0.1s steps: budget = floor = 2.0
            wd.beat(now=i * 0.1)
        self.assertAlmostEqual(wd.budget_s(), 2.0)
        self.assertFalse(wd.stale(now=1.0 + 1.9))
        self.assertTrue(wd.stale(now=1.0 + 2.1))

    def test_ema_adapts_to_regime_change(self):
        # A run that legitimately slows (bigger batches, eval rounds)
        # widens its own budget instead of tripping, as long as each
        # slowdown stays inside the current budget.
        wd = self._wd(floor_s=1.0, multiplier=8.0, alpha=0.5)
        wd.start(now=0.0)
        t = 0.0
        for i in range(1, 11):
            t = i * 0.2
            wd.beat(now=t)
        self.assertAlmostEqual(wd.budget_s(), 1.6)
        for step_s in (1.5, 2.5, 3.0, 3.0, 3.0):  # gradual slowdown
            t += step_s
            self.assertFalse(wd.stale(now=t - 0.001))
            wd.beat(now=t)
        self.assertGreater(wd.budget_s(), 8.0)

    def test_snapshot_forensics(self):
        wd = self._wd()
        wd.start(now=0.0)
        wd.beat(now=1.0)
        wd.beat(now=1.5)
        snap = wd.snapshot()
        self.assertEqual(snap["beats"], 2)
        self.assertAlmostEqual(snap["ema_step_s"], 0.5)
        self.assertIn("budget_s", snap)
        self.assertIn("staleness_s", snap)


if __name__ == "__main__":
    unittest.main()
