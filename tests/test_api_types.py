"""API type round-trip and status-contract tests (reference analog:
``api/v1alpha1`` types + ``cron_util_test.go`` status extraction specs)."""

from datetime import datetime, timezone

from cron_operator_tpu.api.v1alpha1 import (
    API_VERSION,
    ConcurrencyPolicy,
    Cron,
    JobStatus,
    job_status_from_unstructured,
    parse_time,
    rfc3339,
)
from cron_operator_tpu.controller.workload import is_workload_finished


def utc(*args):
    return datetime(*args, tzinfo=timezone.utc)


class TestRoundTrip:
    def test_cron_round_trip(self):
        src = {
            "apiVersion": API_VERSION,
            "kind": "Cron",
            "metadata": {
                "name": "demo",
                "namespace": "default",
                "uid": "u-1",
                "creationTimestamp": "2026-03-01T10:00:00Z",
                "labels": {"a": "b"},
            },
            "spec": {
                "schedule": "*/5 * * * *",
                "concurrencyPolicy": "Forbid",
                "suspend": True,
                "deadline": "2026-04-01T00:00:00Z",
                "historyLimit": 3,
                "template": {
                    "workload": {
                        "apiVersion": "kubeflow.org/v1",
                        "kind": "JAXJob",
                        "spec": {"replicas": 4},
                    }
                },
            },
            "status": {
                "active": [
                    {
                        "apiVersion": "kubeflow.org/v1",
                        "kind": "JAXJob",
                        "name": "demo-123",
                        "namespace": "default",
                        "uid": "u-2",
                        "resourceVersion": "7",
                    }
                ],
                "history": [
                    {
                        "uid": "u-3",
                        "object": {
                            "apiGroup": "kubeflow.org/v1",
                            "kind": "JAXJob",
                            "name": "demo-120",
                        },
                        "status": "Succeeded",
                        "created": "2026-03-01T10:00:00Z",
                        "finished": "2026-03-01T10:05:00Z",
                    }
                ],
                "lastScheduleTime": "2026-03-01T10:05:00Z",
            },
        }
        cron = Cron.from_dict(src)
        assert cron.spec.concurrency_policy == ConcurrencyPolicy.FORBID
        assert cron.spec.history_limit == 3
        assert cron.spec.suspend is True
        assert cron.spec.template.workload["kind"] == "JAXJob"
        assert cron.status.active[0].resource_version == "7"
        assert cron.status.history[0].object.api_group == "kubeflow.org/v1"
        out = cron.to_dict()
        assert out == src

    def test_defaults(self):
        cron = Cron.from_dict(
            {"metadata": {"name": "x"}, "spec": {"schedule": "* * * * *"}}
        )
        assert cron.spec.concurrency_policy == ConcurrencyPolicy.ALLOW
        assert cron.spec.history_limit is None
        assert cron.spec.suspend is None

    def test_rfc3339(self):
        t = utc(2026, 3, 1, 10, 0, 5)
        assert rfc3339(t) == "2026-03-01T10:00:05Z"
        assert parse_time("2026-03-01T10:00:05Z") == t
        assert parse_time(None) is None


def make_workload(conditions):
    return {
        "apiVersion": "kubeflow.org/v1",
        "kind": "JAXJob",
        "metadata": {"name": "j", "namespace": "default"},
        "status": {"conditions": conditions},
    }


class TestStatusContract:
    """Parity with the terminal-state logic specs in
    ``cron_util_test.go:151-231``."""

    def test_no_status(self):
        obj = {"apiVersion": "kubeflow.org/v1", "kind": "JAXJob", "metadata": {}}
        assert job_status_from_unstructured(obj) is None
        final, finished = is_workload_finished(obj)
        assert finished is False and final == ""

    def test_running_not_finished(self):
        w = make_workload(
            [
                {"type": "Created", "status": "True"},
                {"type": "Running", "status": "True"},
            ]
        )
        _, finished = is_workload_finished(w)
        assert finished is False

    def test_succeeded(self):
        w = make_workload(
            [
                {"type": "Created", "status": "True"},
                {"type": "Running", "status": "True"},
                {"type": "Succeeded", "status": "True"},
            ]
        )
        final, finished = is_workload_finished(w)
        assert finished is True and final == "Succeeded"

    def test_failed(self):
        w = make_workload(
            [
                {"type": "Created", "status": "True"},
                {"type": "Failed", "status": "True"},
            ]
        )
        final, finished = is_workload_finished(w)
        assert finished is True and final == "Failed"

    def test_false_terminal_condition_ignored(self):
        w = make_workload(
            [
                {"type": "Succeeded", "status": "False"},
                {"type": "Running", "status": "True"},
            ]
        )
        _, finished = is_workload_finished(w)
        assert finished is False

    def test_final_status_is_last_condition(self):
        # Succeeded=True present but a later Restarting entry is last:
        # the recorded final status is the LAST condition type (reference
        # quirk, ``cron_util.go:85``).
        w = make_workload(
            [
                {"type": "Succeeded", "status": "True"},
                {"type": "Restarting", "status": "True"},
            ]
        )
        final, finished = is_workload_finished(w)
        assert finished is True and final == "Restarting"

    def test_job_status_fields(self):
        status = JobStatus.from_dict(
            {
                "conditions": [{"type": "Running", "status": "True"}],
                "startTime": "2026-03-01T10:00:00Z",
            }
        )
        assert status.start_time == utc(2026, 3, 1, 10, 0)
        assert status.is_finished() is False
