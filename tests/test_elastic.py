"""Elastic training (reshard-on-preemption) tests — the PR-7 pipeline end
to end: ``replan`` mesh shrinking, the CheckpointStore flush-on-teardown
durability guarantee, parallelism-independent cross-shape restore (save on
8 devices, resume on 4 then 2 — the Tenplex property), the ``Preempted``
signal the executor records, and the controller loop that turns that
signal into a resume attempt on a strictly smaller mesh while history
collapses the attempts into one logical run.

All meshes are virtual CPU devices (conftest forces an 8-device host
platform), so the full path runs in CI without TPU hardware.
"""

import time
from itertools import repeat

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cron_operator_tpu.parallel.mesh import (
    DATA_AXIS,
    FSDP_AXIS,
    TENSOR_AXIS,
    mesh_for_devices,
    plan_for_devices,
    replan,
)
from cron_operator_tpu.workloads.checkpoint import (
    CheckpointStore,
    flush_open_stores,
)
from cron_operator_tpu.workloads.train import TrainConfig, Trainer

JAX_AV, JAX_KIND = "kubeflow.org/v1", "JAXJob"
CRON_AV = "apps.kubedl.io/v1alpha1"


def wait_for(fn, timeout=30.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        result = fn()
        if result:
            return result
        time.sleep(interval)
    raise AssertionError("condition not met in time")


# ---------------------------------------------------------------------------
# replan: the reshard plan
# ---------------------------------------------------------------------------


class TestReplan:
    def test_data_axis_absorbs_shrink(self):
        old = plan_for_devices(8, fsdp=2)  # data=4 x fsdp=2
        new = replan(old, 4)
        assert new.n_devices == 4
        assert new.axis(FSDP_AXIS) == 2  # model axis preserved
        assert new.axis(DATA_AXIS) == 2  # shrink landed on data

    def test_model_axes_reduced_when_indivisible(self):
        old = plan_for_devices(8, fsdp=4)
        new = replan(old, 2)  # model par 4 cannot fit in 2
        assert new.n_devices == 2
        assert new.axis(FSDP_AXIS) == 2
        assert new.axis(DATA_AXIS) == 1

    def test_tensor_axis_survives_when_divisible(self):
        old = plan_for_devices(8, tensor=2, fsdp=2)
        new = replan(old, 4)
        assert new.axis(TENSOR_AXIS) == 2
        assert new.axis(FSDP_AXIS) == 2
        assert new.axis(DATA_AXIS) == 1

    def test_same_count_is_identity(self):
        old = plan_for_devices(8, fsdp=2)
        assert replan(old, 8) is old

    def test_accepts_device_sequence(self):
        old = plan_for_devices(8)
        assert replan(old, jax.devices()[:2]).n_devices == 2

    def test_grow_and_empty_rejected(self):
        old = plan_for_devices(4)
        with pytest.raises(ValueError):
            replan(old, 8)  # scale-up is an explicit caller decision
        with pytest.raises(ValueError):
            replan(old, 0)

    def test_explicit_grow_widens_data_first(self):
        old = plan_for_devices(4, fsdp=2)  # data=2 x fsdp=2
        new = replan(old, 8, allow_grow=True)
        assert new.n_devices == 8
        assert new.axis(FSDP_AXIS) == 2  # model axes untouched
        assert new.axis(DATA_AXIS) == 4  # growth landed on data

    def test_grow_restores_shrunk_model_axes(self):
        """The mirror of the shrink rule: growing back to the launch
        width with the launch plan in hand restores the model axes the
        shrink sacrificed, not just the data axis."""
        orig = plan_for_devices(8, fsdp=4)  # data=2 x fsdp=4
        shrunk = replan(orig, 2)  # fsdp halved to fit
        assert shrunk.axis(FSDP_AXIS) == 2
        back = replan(shrunk, 8, allow_grow=True, original_plan=orig)
        assert back.axis(FSDP_AXIS) == 4  # model axis restored
        assert back.axis(DATA_AXIS) == 2  # original factorization

    def test_grow_partial_restore_when_divisible(self):
        orig = plan_for_devices(8, fsdp=4)
        shrunk = replan(orig, 2)  # data=1 x fsdp=2
        mid = replan(shrunk, 4, allow_grow=True, original_plan=orig)
        # 4 devices fit the restored fsdp=4 exactly; data stays 1.
        assert mid.axis(FSDP_AXIS) == 4
        assert mid.axis(DATA_AXIS) == 1

    def test_grow_without_original_stays_data_parallel(self):
        shrunk = plan_for_devices(2, fsdp=2)
        wide = replan(shrunk, 8, allow_grow=True)
        assert wide.axis(FSDP_AXIS) == 2
        assert wide.axis(DATA_AXIS) == 4

    def test_grow_indivisible_rejected(self):
        old = plan_for_devices(4, fsdp=4)
        with pytest.raises(ValueError):
            replan(old, 6, allow_grow=True)  # 6 % fsdp(4) != 0

    def test_regrow_wrapper(self):
        from cron_operator_tpu.parallel.mesh import regrow

        orig = plan_for_devices(8, fsdp=4)
        shrunk = replan(orig, 2)
        back = regrow(shrunk, 8, original_plan=orig)
        assert back.axis(FSDP_AXIS) == 4
        assert back.n_devices == 8


# ---------------------------------------------------------------------------
# CheckpointStore: the flush guarantee (preempt/SIGTERM durability)
# ---------------------------------------------------------------------------


def _tiny_state():
    return {"w": jnp.arange(8, dtype=jnp.float32), "step": jnp.int32(3)}


class TestFlushGuarantee:
    def test_close_flushes_async_save(self, tmp_path):
        store = CheckpointStore("ns", "job-a", root=str(tmp_path))
        store.save(3, _tiny_state())
        store.close()  # no explicit wait(): close IS the flush
        fresh = CheckpointStore("ns", "job-a", root=str(tmp_path))
        assert fresh.latest_step() == 3
        raw = fresh._restore_raw(3)
        assert np.array_equal(np.asarray(raw["w"]), np.arange(8))
        fresh.close()

    def test_flush_open_stores_drains_inflight(self, tmp_path):
        store = CheckpointStore("ns", "job-b", root=str(tmp_path))
        store.save(5, _tiny_state())
        # The executor's preempt path: flush by (namespace, job) without
        # holding the entrypoint's store reference.
        assert flush_open_stores("ns", "job-b") >= 1
        fresh = CheckpointStore("ns", "job-b", root=str(tmp_path))
        assert fresh.latest_step() == 5
        fresh.close()
        store.close()

    def test_close_deregisters(self, tmp_path):
        store = CheckpointStore("ns", "job-c", root=str(tmp_path))
        store.close()
        assert flush_open_stores("ns", "job-c") == 0


# ---------------------------------------------------------------------------
# Cross-shape restore: save on 8 devices, resume on 4, then 2 (Tenplex)
# ---------------------------------------------------------------------------

DIM, CLASSES, BATCH = 16, 10, 8


def _apply(p, x):
    return x @ p["w"] + p["b"]


def _sample(key):
    kx, ky = jax.random.split(key)
    return {
        "x": jax.random.normal(kx, (BATCH, DIM), jnp.float32),
        "y": jax.random.randint(ky, (BATCH,), 0, CLASSES),
    }


def _params0():
    k = jax.random.PRNGKey(7)
    return {
        "w": jax.random.normal(k, (DIM, CLASSES), jnp.float32) * 0.1,
        "b": jnp.zeros((CLASSES,), jnp.float32),
    }


def _trainer(n_devs, store):
    mesh = mesh_for_devices(jax.devices()[:n_devs])
    cfg = TrainConfig(
        optimizer="sgd", learning_rate=0.05, save_every=4, data_seed=3
    )
    return Trainer(_apply, _params0(), mesh, cfg, checkpoint=store,
                   sample_fn=_sample)


def _losses(stats):
    return {s.step: s.loss for s in stats if s.loss is not None}


@pytest.fixture(scope="module")
def cross_shape(tmp_path_factory):
    """One elastic chain (8 → 4 → 2 devices) plus an uninterrupted
    reference run, shared by the assertions below (compiling four train
    steps once instead of per-test)."""
    root = str(tmp_path_factory.mktemp("xshape"))

    ref_store = CheckpointStore("t", "ref", root=root)
    ref = _trainer(8, ref_store)
    ref_losses = _losses(ref.run(repeat({}), 12))
    ref_store.close()

    s1 = CheckpointStore("t", "job", root=root)
    t1 = _trainer(8, s1)
    l1 = _losses(t1.run(repeat({}), 6))  # checkpoint lands at step 4
    s1.close()

    s2 = CheckpointStore("t", "job", root=root)
    t2 = _trainer(4, s2)  # fresh manager: restore path, not save cache
    resumed2 = t2.steps_done
    # Snapshot what the 4-device mesh restored BEFORE it trains on.
    restored4 = jax.tree_util.tree_map(np.asarray, t2.state.params)
    raw8 = s2.restore_params(4)  # the step-4 save, as written on 8 devs
    l2 = _losses(t2.run(repeat({}), 9))  # checkpoint lands at step 8
    s2.close()

    s3 = CheckpointStore("t", "job", root=root)
    t3 = _trainer(2, s3)
    resumed3 = t3.steps_done
    l3 = _losses(t3.run(repeat({}), 12))
    s3.close()

    chain = {}
    chain.update(l1)
    chain.update(l2)
    chain.update(l3)
    return {
        "ref": ref_losses,
        "chain": chain,
        "resumed": (resumed2, resumed3),
        "raw8": raw8,
        "restored4": restored4,
    }


class TestCrossShapeRestore:
    def test_resumes_land_on_checkpoint_steps(self, cross_shape):
        # 8-dev leg saved at 4 (ran to 6), 4-dev leg saved at 8 (ran to 9):
        # each resume starts from the last completed save, losing at most
        # steps since that save — never a completed one.
        assert cross_shape["resumed"] == (4, 8)

    def test_restored_params_bit_exact(self, cross_shape):
        """The params the 4-device mesh restored are bit-for-bit the
        params the 8-device mesh saved — resharding moves bytes, never
        rounds them."""
        raw8 = cross_shape["raw8"]  # host copy of the step-4 save
        restored4 = cross_shape["restored4"]
        assert set(raw8) == set(restored4) == {"w", "b"}
        for leaf in ("w", "b"):
            assert np.array_equal(
                np.asarray(raw8[leaf]), restored4[leaf]
            ), leaf

    def test_loss_curve_continues(self, cross_shape):
        ref, chain = cross_shape["ref"], cross_shape["chain"]
        assert sorted(chain) == sorted(ref) == list(range(1, 13))
        # Same-mesh prefix (steps 1-6 ran on the identical 8-dev mesh in
        # both runs): bit-for-bit.
        for step in range(1, 7):
            assert np.float32(chain[step]) == np.float32(ref[step]), step
        # Cross-mesh continuation: the batch at step k is derived from
        # fold_in(data_seed, k) regardless of mesh, so the curve continues
        # exactly up to summation order — a 1-ulp reduction-order wobble
        # is the only permitted difference.
        for step in range(7, 13):
            assert np.isclose(
                chain[step], ref[step], rtol=0.0, atol=1e-6
            ), (step, chain[step], ref[step])


class TestRestoreResharded:
    def test_bitwise_roundtrip_onto_smaller_mesh(self, tmp_path):
        """Direct unit for the host-side reshard fallback: every leaf the
        2-device template receives equals the 8-device save exactly."""
        mesh8 = mesh_for_devices(jax.devices()[:8])
        state = {
            "w": jax.device_put(
                jnp.arange(64, dtype=jnp.float32).reshape(8, 8),
                jax.sharding.NamedSharding(
                    mesh8, jax.sharding.PartitionSpec(DATA_AXIS)
                ),
            ),
            "step": jnp.int32(9),
        }
        store = CheckpointStore("ns", "rt", root=str(tmp_path))
        store.save(9, state)
        store.wait()
        store.close()

        mesh2 = mesh_for_devices(jax.devices()[:2])
        like = {
            "w": jax.device_put(
                jnp.zeros((8, 8), jnp.float32),
                jax.sharding.NamedSharding(
                    mesh2, jax.sharding.PartitionSpec(DATA_AXIS)
                ),
            ),
            "step": jnp.int32(0),
        }
        fresh = CheckpointStore("ns", "rt", root=str(tmp_path))
        out = fresh.restore_resharded(9, like)
        fresh.close()
        assert out["w"].sharding.mesh.devices.size == 2
        assert np.array_equal(
            np.asarray(out["w"]), np.asarray(state["w"])
        )
        assert int(out["step"]) == 9

    def test_bitwise_roundtrip_onto_larger_mesh(self, tmp_path):
        """Grow direction of the same contract: a save written on a
        2-device mesh restores bit-for-bit onto an 8-device template —
        checkpoint-and-regrow never rounds a parameter byte."""
        mesh2 = mesh_for_devices(jax.devices()[:2])
        state = {
            "w": jax.device_put(
                jnp.arange(64, dtype=jnp.float32).reshape(8, 8),
                jax.sharding.NamedSharding(
                    mesh2, jax.sharding.PartitionSpec(DATA_AXIS)
                ),
            ),
            "step": jnp.int32(5),
        }
        store = CheckpointStore("ns", "gw", root=str(tmp_path))
        store.save(5, state)
        store.wait()
        store.close()

        mesh8 = mesh_for_devices(jax.devices()[:8])
        like = {
            "w": jax.device_put(
                jnp.zeros((8, 8), jnp.float32),
                jax.sharding.NamedSharding(
                    mesh8, jax.sharding.PartitionSpec(DATA_AXIS)
                ),
            ),
            "step": jnp.int32(0),
        }
        fresh = CheckpointStore("ns", "gw", root=str(tmp_path))
        out = fresh.restore_resharded(5, like)
        fresh.close()
        assert out["w"].sharding.mesh.devices.size == 8
        assert np.array_equal(
            np.asarray(out["w"]), np.asarray(state["w"])
        )
        assert int(out["step"]) == 5


# ---------------------------------------------------------------------------
# Grow-direction cross-shape: save on 2 devices, regrow onto 4, then 8
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def cross_shape_grow(tmp_path_factory):
    """The mirror of ``cross_shape``: one elastic chain growing 2 → 4 →
    8 devices plus an uninterrupted 2-device reference. The second grow
    lands exactly on a ``save_every`` boundary (leg 2 stops at step 8
    with save_every=4), so the regrown leg resumes with zero lost steps."""
    root = str(tmp_path_factory.mktemp("xgrow"))

    ref_store = CheckpointStore("t", "gref", root=root)
    ref = _trainer(2, ref_store)
    ref_losses = _losses(ref.run(repeat({}), 12))
    ref_store.close()

    s1 = CheckpointStore("t", "gjob", root=root)
    t1 = _trainer(2, s1)
    l1 = _losses(t1.run(repeat({}), 6))  # checkpoint lands at step 4
    s1.close()

    s2 = CheckpointStore("t", "gjob", root=root)
    t2 = _trainer(4, s2)  # first grow: restore 2-dev save on 4 devices
    resumed2 = t2.steps_done
    restored4 = jax.tree_util.tree_map(np.asarray, t2.state.params)
    raw2 = s2.restore_params(4)  # the step-4 save as written on 2 devs
    l2 = _losses(t2.run(repeat({}), 8))  # stops ON the save boundary
    s2.close()

    s3 = CheckpointStore("t", "gjob", root=root)
    t3 = _trainer(8, s3)  # second grow: resumes from the boundary save
    resumed3 = t3.steps_done
    l3 = _losses(t3.run(repeat({}), 12))
    s3.close()

    chain = {}
    chain.update(l1)
    chain.update(l2)
    chain.update(l3)
    return {
        "ref": ref_losses,
        "chain": chain,
        "resumed": (resumed2, resumed3),
        "raw2": raw2,
        "restored4": restored4,
    }


class TestCrossShapeGrow:
    def test_resumes_land_on_checkpoint_steps(self, cross_shape_grow):
        # 2-dev leg saved at 4 (ran to 6); 4-dev leg stopped exactly on
        # the step-8 save boundary, so the 8-dev leg loses zero steps.
        assert cross_shape_grow["resumed"] == (4, 8)

    def test_restored_params_bit_exact(self, cross_shape_grow):
        """What the 4-device mesh restored is bit-for-bit the 2-device
        save — growing moves bytes across more devices, never rounds."""
        raw2 = cross_shape_grow["raw2"]
        restored4 = cross_shape_grow["restored4"]
        assert set(raw2) == set(restored4) == {"w", "b"}
        for leaf in ("w", "b"):
            assert np.array_equal(
                np.asarray(raw2[leaf]), restored4[leaf]
            ), leaf

    def test_loss_curve_continues(self, cross_shape_grow):
        ref, chain = cross_shape_grow["ref"], cross_shape_grow["chain"]
        assert sorted(chain) == sorted(ref) == list(range(1, 13))
        # Same-mesh prefix (steps 1-6 ran on the identical 2-dev mesh in
        # both runs): bit-for-bit.
        for step in range(1, 7):
            assert np.float32(chain[step]) == np.float32(ref[step]), step
        # Cross-mesh continuation after each grow: batch at step k is
        # fold_in(data_seed, k) regardless of mesh, so only a 1-ulp
        # reduction-order wobble is permitted.
        for step in range(7, 13):
            assert np.isclose(
                chain[step], ref[step], rtol=0.0, atol=1e-6
            ), (step, chain[step], ref[step])


# ---------------------------------------------------------------------------
# The Preempted signal (executor side)
# ---------------------------------------------------------------------------


class TestPreemptedSignal:
    def test_condition_record_and_metrics(self):
        from cron_operator_tpu.backends.local import LocalExecutor
        from cron_operator_tpu.runtime.faults import FaultInjector, FaultPlan
        from cron_operator_tpu.runtime.kube import APIServer
        from cron_operator_tpu.runtime.manager import Metrics

        api = APIServer()
        metrics = Metrics()
        injector = FaultInjector(api, FaultPlan.quiet(seed=1))
        injector.instrument(metrics)
        ex = LocalExecutor(api, metrics=metrics)
        ex.start()
        try:
            api.create({
                "apiVersion": JAX_AV, "kind": JAX_KIND,
                "metadata": {
                    "name": "victim", "namespace": "default",
                    "annotations": {
                        "tpu.kubedl.io/simulate-duration": "30s",
                    },
                },
                "spec": {},
            })
            wait_for(lambda: "Running" in [
                c["type"] for c in (api.get(
                    JAX_AV, JAX_KIND, "default", "victim"
                ).get("status") or {}).get("conditions", [])
            ])
            prior = ex.capacity()
            record = injector.inject_preempt(
                ex, "default", "victim", lost_devices=2
            )
            obj = api.get(JAX_AV, JAX_KIND, "default", "victim")
            conds = (obj.get("status") or {}).get("conditions") or []
            types = [c["type"] for c in conds]
            # Distinct Preempted cause, then the terminal outcome LAST
            # (the Kubeflow convention reads the final condition as the
            # job's status — "Preempted" must never be it).
            assert "Preempted" in types
            assert types[-1] == "Failed"
            assert types.index("Preempted") < types.index("Failed")
            by_type = {c["type"]: c for c in conds}
            assert by_type["Preempted"]["reason"] == "TPUSlicePreempted"
            assert by_type["Failed"]["reason"] == "TPUSlicePreempted"
            # The capacity snapshot elastic resume replans against.
            pre = (obj.get("status") or {}).get("preemption") or {}
            assert pre["priorDevices"] == prior
            assert pre["lostDevices"] == 2
            assert pre["survivingDevices"] == prior - 2
            assert pre["preemptedAt"]
            assert record["survivingDevices"] == prior - 2
            assert ex.capacity() == prior - 2
            ex.restore_capacity()
            assert ex.capacity() == prior
            assert metrics.get("cron_workload_preemptions_total") == 1.0
            assert metrics.get(
                'faults_injected_total{kind="preempt"}'
            ) == 1.0
        finally:
            ex.stop()


# ---------------------------------------------------------------------------
# End to end: preempt a cron's training job, resume on a smaller mesh
# ---------------------------------------------------------------------------


def _register_paced_entrypoint():
    """A real training entrypoint (the full param/checkpoint/progress
    surface via the entrypoints helpers) paced to ``param.pace_s`` per
    step, so the preemption deterministically lands mid-run — the stock
    workloads finish faster than the 1 s progress-publish throttle."""
    from cron_operator_tpu.backends.registry import register_entrypoint
    from cron_operator_tpu.workloads import entrypoints as eps

    @register_entrypoint("test-elastic-paced")
    def paced_train(ctx):
        steps = int(ctx.params.get("steps", 20))
        pace = float(ctx.params.get("pace_s", 0.05))
        devs = eps._devices(ctx)
        with jax.default_device(devs[0]):
            mesh = eps._mesh(ctx, devs)
            trainer = Trainer(
                _apply, _params0(), mesh,
                TrainConfig(**eps._train_kwargs(
                    ctx, steps, optimizer="sgd", learning_rate=0.05,
                    data_seed=3,
                )),
                checkpoint=eps._checkpoint_store(ctx),
                sample_fn=_sample,  # fused: batches below only pace
            )

            def paced_batches():
                while True:
                    time.sleep(pace)
                    yield {}

            eps._run(ctx, trainer, paced_batches(), steps)


class TestElasticEndToEnd:
    def test_preempted_job_resumes_on_smaller_mesh(self, tmp_path):
        from cron_operator_tpu.api.v1alpha1 import Cron
        from cron_operator_tpu.backends.local import LocalExecutor
        from cron_operator_tpu.controller.cron_controller import CronReconciler
        from cron_operator_tpu.runtime.kube import APIServer
        from cron_operator_tpu.runtime.manager import Metrics

        _register_paced_entrypoint()
        api = APIServer()  # real clock: training is real wall time
        metrics = Metrics()
        ex = LocalExecutor(api, metrics=metrics)
        ex.start()
        rec = CronReconciler(api, metrics=metrics)
        try:
            api.create({
                "apiVersion": CRON_AV, "kind": "Cron",
                "metadata": {"name": "elastic", "namespace": "default"},
                "spec": {
                    "schedule": "@every 1s",
                    "concurrencyPolicy": "Forbid",
                    "template": {"workload": {
                        "apiVersion": JAX_AV, "kind": JAX_KIND,
                        "metadata": {"annotations": {
                            "tpu.kubedl.io/entrypoint": "test-elastic-paced",
                            "tpu.kubedl.io/elastic-resume": "true",
                            "tpu.kubedl.io/param.steps": "60",
                            "tpu.kubedl.io/param.pace_s": "0.05",
                            "tpu.kubedl.io/param.save_every": "3",
                            "tpu.kubedl.io/param.checkpoint": "1",
                            "tpu.kubedl.io/param.checkpoint_dir": str(tmp_path),
                            "tpu.kubedl.io/param.platform": "cpu",
                            "tpu.kubedl.io/param.fsdp": "2",
                        }},
                        "spec": {},
                    }},
                },
            })

            def sweep():
                rec.reconcile("default", "elastic")

            def progress(name):
                obj = api.try_get(JAX_AV, JAX_KIND, "default", name)
                if obj is None:
                    return {}
                return (obj.get("status") or {}).get(
                    "trainingProgress"
                ) or {}

            # Fire the first tick (real clock, @every 1s).
            def tick():
                sweep()
                return api.list(JAX_AV, JAX_KIND, namespace="default")

            jobs = wait_for(tick, timeout=15.0, interval=0.3)
            root = jobs[0]["metadata"]["name"]

            # Let it clear the first checkpoint interval, then preempt
            # half the slice away mid-run.
            wait_for(
                lambda: int(progress(root).get("steps_done") or 0) >= 5,
                timeout=90.0,
            )
            record = ex.preempt("default", root, lost_devices=4)
            assert record["survivingDevices"] == 4

            # One sweep against the degraded capacity submits the resume.
            sweep()
            rname = f"{root}-r1"
            rj = api.get(JAX_AV, JAX_KIND, "default", rname)
            ann = rj["metadata"]["annotations"]
            assert ann["tpu.kubedl.io/resume-of"] == root
            assert ann["tpu.kubedl.io/resume-attempt"] == "1"
            assert ann["tpu.kubedl.io/param.devices"] == "4"  # smaller mesh
            assert ann["tpu.kubedl.io/param.fsdp"] == "2"  # model axis kept
            assert ann["tpu.kubedl.io/param.checkpoint_job"] == root
            # While the resume is in flight: it is the cron's active run
            # and the logical run stays OUT of history.
            cron = Cron.from_dict(
                api.get(CRON_AV, "Cron", "default", "elastic")
            )
            assert [a.name for a in cron.status.active] == [rname]
            assert cron.status.history == []

            def done():
                conds = (api.get(
                    JAX_AV, JAX_KIND, "default", rname
                ).get("status") or {}).get("conditions") or []
                return conds and conds[-1]["type"] in (
                    "Succeeded", "Failed"
                )

            wait_for(done, timeout=120.0)
            sweep()

            prog = progress(rname)
            # Resumed from the latest completed save, not step 0, and
            # trained through to the original target.
            assert int(prog.get("resumed_from_step") or 0) >= 3
            assert int(prog.get("steps_done") or 0) == 60
            cron = Cron.from_dict(
                api.get(CRON_AV, "Cron", "default", "elastic")
            )
            hist = cron.status.history
            assert len(hist) == 1  # one LOGICAL run, not two attempts
            assert hist[0].status == "Succeeded"
            assert hist[0].resumes == 1
            assert hist[0].last_resumed_at is not None
            assert hist[0].object.name == root  # keyed by the root attempt
            assert metrics.get("cron_workload_resumes_total") == 1.0
        finally:
            ex.stop()
