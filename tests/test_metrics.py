"""Metrics exposition + the north-star latency histogram (VERDICT r3 #5).

The reference exposes only controller-runtime built-ins and registers no
custom metrics (SURVEY.md §5); this build adds domain counters and —
asserted here — ``cron_tick_to_first_step_seconds``, the quantity the
BASELINE.md north star is stated in, derived operator-side from workload
status and served with proper ``# HELP``/``# TYPE`` headers so a real
Prometheus scrape (the chart's ServiceMonitor) ingests it.
"""

from __future__ import annotations

import urllib.request

import pytest

from cron_operator_tpu.controller import CronReconciler
from cron_operator_tpu.runtime.manager import (
    PROMETHEUS_CONTENT_TYPE,
    Metrics,
)


def _cron(name="c", schedule="*/5 * * * *"):
    return {
        "apiVersion": "apps.kubedl.io/v1alpha1",
        "kind": "Cron",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {
            "schedule": schedule,
            "template": {
                "workload": {
                    "apiVersion": "kubeflow.org/v1",
                    "kind": "JAXJob",
                    "spec": {"replicaSpecs": {"Worker": {"replicas": 1}}},
                }
            },
        },
    }


class TestMetricsRegistry:
    def test_counter_families_get_type_and_help(self):
        m = Metrics()
        m.inc('cron_ticks_fired_total')
        m.inc('controller_runtime_reconcile_total{controller="cron",'
              'result="success"}', 2)
        text = m.render_prometheus()
        assert "# TYPE cron_ticks_fired_total counter" in text
        assert "# HELP cron_ticks_fired_total" in text
        assert "# TYPE controller_runtime_reconcile_total counter" in text
        # one TYPE line per family even with multiple label sets
        m.inc('controller_runtime_reconcile_total{controller="cron",'
              'result="requeue_after"}')
        text = m.render_prometheus()
        assert text.count("# TYPE controller_runtime_reconcile_total") == 1

    def test_histogram_cumulative_buckets(self):
        m = Metrics()
        m.observe("cron_tick_to_first_step_seconds", 3.0,
                  buckets=(1.0, 5.0, 10.0))
        m.observe("cron_tick_to_first_step_seconds", 7.0,
                  buckets=(1.0, 5.0, 10.0))
        m.observe("cron_tick_to_first_step_seconds", 99.0,
                  buckets=(1.0, 5.0, 10.0))
        text = m.render_prometheus()
        assert "# TYPE cron_tick_to_first_step_seconds histogram" in text
        assert 'cron_tick_to_first_step_seconds_bucket{le="1"} 0' in text
        assert 'cron_tick_to_first_step_seconds_bucket{le="5"} 1' in text
        assert 'cron_tick_to_first_step_seconds_bucket{le="10"} 2' in text
        assert 'cron_tick_to_first_step_seconds_bucket{le="+Inf"} 3' in text
        assert "cron_tick_to_first_step_seconds_sum 109.0" in text
        assert "cron_tick_to_first_step_seconds_count 3" in text

    def test_gauges_render_with_type_and_last_write_wins(self):
        m = Metrics()
        m.set("workload_tokens_per_s", 1000.0)
        m.set("workload_tokens_per_s", 2500.5)
        m.set('workqueue_depth{name="cron"}', 3)
        text = m.render_prometheus()
        assert "# TYPE workload_tokens_per_s gauge" in text
        assert "workload_tokens_per_s 2500.5" in text
        assert "# TYPE workqueue_depth gauge" in text
        assert 'workqueue_depth{name="cron"} 3.0' in text
        assert m.gauge("workload_tokens_per_s") == 2500.5

    def test_labeled_histogram_series_share_family_headers(self):
        m = Metrics()
        m.observe('cron_tick_phase_seconds{phase="queue"}', 0.2,
                  buckets=(1.0, 5.0))
        m.observe('cron_tick_phase_seconds{phase="compile"}', 3.0,
                  buckets=(1.0, 5.0))
        text = m.render_prometheus()
        assert text.count("# TYPE cron_tick_phase_seconds histogram") == 1
        # `le` renders last inside the label block, after the series labels
        assert ('cron_tick_phase_seconds_bucket{phase="compile",le="5"} 1'
                in text)
        assert ('cron_tick_phase_seconds_bucket{phase="queue",le="1"} 1'
                in text)
        assert 'cron_tick_phase_seconds_sum{phase="queue"} 0.2' in text
        assert 'cron_tick_phase_seconds_count{phase="compile"} 1' in text

    def test_conflicting_buckets_raise_value_error(self):
        m = Metrics()
        m.observe('cron_tick_phase_seconds{phase="queue"}', 0.2,
                  buckets=(1.0, 5.0))
        with pytest.raises(ValueError, match="cron_tick_phase_seconds"):
            m.observe('cron_tick_phase_seconds{phase="compile"}', 3.0,
                      buckets=(2.0, 4.0))
        # same ladder (any series of the family) stays accepted
        m.observe('cron_tick_phase_seconds{phase="compile"}', 3.0,
                  buckets=(1.0, 5.0))

    def test_exposition_content_type_is_prometheus_004(self):
        assert (PROMETHEUS_CONTENT_TYPE
                == "text/plain; version=0.0.4; charset=utf-8")


class TestNorthStarObservation:
    def _workload_with_progress(self, api, cron_name, name, first_step_delay):
        """Create a labeled workload, then stamp trainingProgress so its
        first step lands `first_step_delay` seconds after creation."""
        api.create({
            "apiVersion": "kubeflow.org/v1",
            "kind": "JAXJob",
            "metadata": {
                "name": name, "namespace": "default",
                "labels": {"kubedl.io/cron-name": cron_name},
            },
            "spec": {"replicaSpecs": {"Worker": {"replicas": 1}}},
        })
        created = api.get("kubeflow.org/v1", "JAXJob", "default", name)
        from cron_operator_tpu.api.v1alpha1 import parse_time

        t0 = parse_time(created["metadata"]["creationTimestamp"]).timestamp()
        api.patch_status(
            "kubeflow.org/v1", "JAXJob", "default", name,
            {"trainingProgress": {"first_step_at": t0 + first_step_delay}},
        )

    def test_latency_observed_once_per_workload(self, api, fake_clock):
        metrics = Metrics()
        rec = CronReconciler(api, metrics=metrics)
        api.create(_cron())
        self._workload_with_progress(api, "c", "c-1111", 12.0)

        rec.reconcile("default", "c")
        h = metrics.histogram("cron_tick_to_first_step_seconds")
        assert h is not None and h["count"] == 1
        assert abs(h["sum"] - 12.0) < 1.5  # rfc3339 whole-second precision

        # Re-reconciling must not double-count the same workload.
        rec.reconcile("default", "c")
        h = metrics.histogram("cron_tick_to_first_step_seconds")
        assert h["count"] == 1

        # A second workload contributes its own observation.
        self._workload_with_progress(api, "c", "c-2222", 40.0)
        rec.reconcile("default", "c")
        h = metrics.histogram("cron_tick_to_first_step_seconds")
        assert h["count"] == 2
        assert abs(h["sum"] - 52.0) < 3.0

    def test_endpoint_serves_the_north_star(self, api):
        """The /metrics endpoint (what the chart's ServiceMonitor scrapes)
        must contain the latency family, headers included."""
        from cron_operator_tpu.cli.main import _serve

        metrics = Metrics()
        rec = CronReconciler(api, metrics=metrics)
        api.create(_cron())
        self._workload_with_progress(api, "c", "c-1111", 30.0)
        rec.reconcile("default", "c")

        server = _serve(
            0,
            {"/metrics": lambda: (metrics.render_prometheus(),
                                  "text/plain")},
            "test-metrics",
        )
        try:
            port = server.server_address[1]
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=5
            ) as resp:
                body = resp.read().decode()
        finally:
            server.shutdown()
        assert "# TYPE cron_tick_to_first_step_seconds histogram" in body
        assert 'cron_tick_to_first_step_seconds_bucket{le="30"} 1' in body
        assert "cron_tick_to_first_step_seconds_count 1" in body


class TestSecureMetrics:
    """VERDICT r4 #2: /metrics over TLS with bearer authn — the embedded
    analog of the reference's secure-metrics stack
    (cmd/operator/start.go:87-150)."""

    def _serve_tls(self, token=None, enable_http2=False):
        from cron_operator_tpu.cli.main import _serve
        from cron_operator_tpu.utils.tlsutil import (
            self_signed_cert,
            server_context,
        )

        cert, key = self_signed_cert()
        ctx = server_context(cert, key, enable_http2=enable_http2)
        server = _serve(
            0,
            {"/metrics": lambda: ("# TYPE up gauge\nup 1\n", "text/plain")},
            "test-secure-metrics",
            tls_ctx=ctx,
            token=token,
        )
        return server, cert

    def _client_ctx(self, cert):
        import ssl

        # Verify against the self-signed cert itself: proves the
        # generated cert is valid for 127.0.0.1, not just that TLS
        # happens to be on.
        ctx = ssl.create_default_context(cafile=cert)
        ctx.check_hostname = False
        return ctx

    def test_scrape_with_token_ok_without_token_rejected(self):
        import urllib.error

        server, cert = self._serve_tls(token="s3cret")
        try:
            port = server.server_address[1]
            url = f"https://127.0.0.1:{port}/metrics"
            ctx = self._client_ctx(cert)

            req = urllib.request.Request(
                url, headers={"Authorization": "Bearer s3cret"}
            )
            with urllib.request.urlopen(req, timeout=5, context=ctx) as r:
                assert r.status == 200
                assert "up 1" in r.read().decode()

            for headers in ({}, {"Authorization": "Bearer wrong"}):
                req = urllib.request.Request(url, headers=headers)
                try:
                    urllib.request.urlopen(req, timeout=5, context=ctx)
                    raise AssertionError("unauthenticated scrape passed")
                except urllib.error.HTTPError as err:
                    assert err.code == 401
        finally:
            server.shutdown()

    def test_http2_refused_at_alpn_by_default(self):
        import socket
        import ssl

        server, cert = self._serve_tls()
        try:
            port = server.server_address[1]
            ctx = self._client_ctx(cert)
            ctx.set_alpn_protocols(["h2", "http/1.1"])
            with socket.create_connection(("127.0.0.1", port), 5) as raw:
                with ctx.wrap_socket(raw) as tls:
                    # The CVE-mitigation default: the server never
                    # selects h2 even when the client prefers it.
                    assert tls.selected_alpn_protocol() == "http/1.1"
        finally:
            server.shutdown()

    def test_cert_watcher_reloads_rotated_pair(self, tmp_path):
        import shutil

        from cron_operator_tpu.utils.tlsutil import (
            CertWatcher,
            self_signed_cert,
            server_context,
        )

        cert, key = self_signed_cert(dir=str(tmp_path / "a"))
        ctx = server_context(cert, key)
        watcher = CertWatcher(ctx, cert, key)  # not started: poll by hand
        assert watcher.poll_once() is False  # unchanged → no reload

        cert2, key2 = self_signed_cert(
            common_name="rotated", dir=str(tmp_path / "b")
        )
        shutil.copy(cert2, cert)
        shutil.copy(key2, key)
        assert watcher.poll_once() is True
        assert watcher.reloads == 1
        assert watcher.poll_once() is False  # stable again

        # Half-written rotation (key truncated): keep the old pair.
        with open(key, "w"):
            pass
        assert watcher.poll_once() is False
        assert watcher.reloads == 1


class TestScrapeAuthenticator:
    """Kube-delegated scrape authn/z (runtime/authfilter.py) — the
    cluster-mode FilterProvider analog (reference start.go:121-133)."""

    class FakeClient:
        def __init__(self, users=None, allowed=None, fail=False):
            self.users = users or {}      # token -> (username, groups)
            self.allowed = allowed or set()  # usernames allowed GET /metrics
            self.fail = fail
            self.review_calls = 0

        def token_review(self, token):
            if self.fail:
                raise RuntimeError("apiserver down")
            self.review_calls += 1
            if token not in self.users:
                return {"authenticated": False}
            name, groups = self.users[token]
            return {"authenticated": True,
                    "user": {"username": name, "groups": groups}}

        def subject_access_review(self, user, groups, verb, path):
            assert (verb, path) == ("get", "/metrics")
            return user in self.allowed

    def _auth(self, **kw):
        from cron_operator_tpu.runtime.authfilter import ScrapeAuthenticator

        client = self.FakeClient(**kw)
        return client, ScrapeAuthenticator(client, ttl_s=60.0)

    def test_authenticated_and_authorized(self):
        _, auth = self._auth(
            users={"tok": ("system:serviceaccount:monitoring:prom", [])},
            allowed={"system:serviceaccount:monitoring:prom"},
        )
        assert auth.allow("Bearer tok") is True

    def test_unknown_token_and_unauthorized_user_denied(self):
        _, auth = self._auth(
            users={"tok": ("someone", [])}, allowed=set(),
        )
        assert auth.allow("Bearer nope") is False   # authn fails
        assert auth.allow("Bearer tok") is False    # authz fails
        assert auth.allow(None) is False
        assert auth.allow("Basic Zm9v") is False
        assert auth.allow("Bearer ") is False

    def test_results_are_cached_per_token(self):
        client, auth = self._auth(
            users={"tok": ("prom", [])}, allowed={"prom"},
        )
        for _ in range(5):
            assert auth.allow("Bearer tok") is True
        assert client.review_calls == 1  # TTL cache absorbed the rest

    def test_fails_closed_when_apiserver_unreachable(self):
        _, auth = self._auth(fail=True)
        assert auth.allow("Bearer tok") is False

    def test_end_to_end_through_stub_kube_reviews(self, tmp_path):
        """The full cluster-mode loop over real sockets: a stub speaking
        the kube review dialect ← ClusterAPIServer ← ScrapeAuthenticator
        ← _serve(authn=...) ← urllib scrape."""
        import json as _json
        import urllib.error
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
        import threading

        SA = "system:serviceaccount:monitoring:prometheus"

        class Stub(BaseHTTPRequestHandler):
            def do_POST(self):
                body = _json.loads(
                    self.rfile.read(int(self.headers["Content-Length"]))
                )
                if "tokenreviews" in self.path:
                    tok = body["spec"]["token"]
                    status = (
                        {"authenticated": True,
                         "user": {"username": SA, "groups": []}}
                        if tok == "sa-token" else {"authenticated": False}
                    )
                else:
                    status = {"allowed": body["spec"]["user"] == SA}
                data = _json.dumps({"status": status}).encode()
                self.send_response(201)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def log_message(self, *a):
                pass

        stub = ThreadingHTTPServer(("127.0.0.1", 0), Stub)
        threading.Thread(target=stub.serve_forever, daemon=True).start()
        try:
            from cron_operator_tpu.cli.main import _serve
            from cron_operator_tpu.runtime.authfilter import (
                ScrapeAuthenticator,
            )
            from cron_operator_tpu.runtime.cluster import (
                ClusterAPIServer,
                ClusterConfig,
            )

            kube = ClusterAPIServer(
                ClusterConfig(f"http://127.0.0.1:{stub.server_port}")
            )
            auth = ScrapeAuthenticator(kube)
            srv = _serve(
                0, {"/metrics": lambda: ("up 1\n", "text/plain")},
                "t-authn", authn=auth.allow,
            )
            try:
                url = f"http://127.0.0.1:{srv.server_address[1]}/metrics"
                req = urllib.request.Request(
                    url, headers={"Authorization": "Bearer sa-token"}
                )
                with urllib.request.urlopen(req, timeout=5) as r:
                    assert r.status == 200
                req = urllib.request.Request(
                    url, headers={"Authorization": "Bearer stolen"}
                )
                try:
                    urllib.request.urlopen(req, timeout=5)
                    raise AssertionError("bad token passed")
                except urllib.error.HTTPError as err:
                    assert err.code == 401
            finally:
                srv.shutdown()
                kube.stop()
        finally:
            stub.shutdown()

    def test_transient_failure_denies_but_is_not_cached(self):
        """An apiserver blip must deny the in-flight scrape (fail
        closed) without locking the token out for the TTL."""
        client, auth = self._auth(
            users={"tok": ("prom", [])}, allowed={"prom"},
        )
        client.fail = True
        assert auth.allow("Bearer tok") is False
        client.fail = False  # apiserver recovers
        assert auth.allow("Bearer tok") is True  # immediately, no TTL wait
