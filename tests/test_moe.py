"""Expert parallelism (parallel.moe): routing parity with a per-token
reference, capacity-overflow semantics, expert-sharded execution parity,
and gradients — on the virtual 8-device CPU mesh (conftest)."""

import jax
import jax.numpy as jnp
import numpy as np

from cron_operator_tpu.parallel.mesh import EXPERT_AXIS, mesh_for_devices
from cron_operator_tpu.parallel.moe import (
    init_moe_params,
    moe_ffn,
    moe_param_sharding,
    router_top1,
)

D, F, E = 8, 16, 4


def _reference_moe(params, x, capacity):
    """Per-token Python reference for Switch top-1 with capacity drop."""
    probs = np.asarray(jax.nn.softmax(x @ params["router"], axis=-1))
    counts = [0] * E
    out = np.zeros_like(np.asarray(x))
    for t in range(x.shape[0]):
        e = int(np.argmax(probs[t]))
        if counts[e] >= capacity:
            continue  # dropped
        counts[e] += 1
        h = np.asarray(
            jax.nn.gelu(jnp.asarray(x[t]) @ params["wi"][e])
        )
        out[t] = (h @ np.asarray(params["wo"][e])) * probs[t, e]
    return out


class TestRouting:
    def test_dispatch_combine_shapes_and_slots(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (12, D))
        params = init_moe_params(jax.random.PRNGKey(1), d_model=D, d_ff=F,
                                 n_experts=E)
        combine, dispatch, aux = router_top1(x @ params["router"], 3)
        assert combine.shape == (12, E, 3)
        assert dispatch.shape == (12, E, 3)
        # Each kept token occupies exactly one (expert, slot); each
        # (expert, slot) holds at most one token.
        per_token = np.asarray(dispatch.sum(axis=(1, 2)))
        assert set(per_token.tolist()) <= {0.0, 1.0}
        per_slot = np.asarray(dispatch.sum(axis=0))
        assert per_slot.max() <= 1.0
        assert float(aux) > 0.0

    def test_matches_per_token_reference(self):
        x = jax.random.normal(jax.random.PRNGKey(2), (32, D))
        params = init_moe_params(jax.random.PRNGKey(3), d_model=D, d_ff=F,
                                 n_experts=E)
        y, _ = moe_ffn(params, x, capacity_factor=1.25)
        capacity = max(1, int(np.ceil(32 / E * 1.25)))
        ref = _reference_moe(params, np.asarray(x), capacity)
        np.testing.assert_allclose(np.asarray(y), ref, rtol=1e-4, atol=1e-4)

    def test_overflow_tokens_are_dropped_to_zero(self):
        """Tiny capacity forces drops; dropped rows must be exactly 0."""
        x = jax.random.normal(jax.random.PRNGKey(4), (16, D))
        params = init_moe_params(jax.random.PRNGKey(5), d_model=D, d_ff=F,
                                 n_experts=E)
        combine, dispatch, _ = router_top1(x @ params["router"], 1)
        kept = np.asarray(dispatch.sum(axis=(1, 2))) > 0
        assert kept.sum() <= E  # at most capacity·E tokens survive
        y, _ = moe_ffn(params, x, capacity_factor=1.0 / (16 / E))
        dropped_rows = np.asarray(y)[~kept]
        np.testing.assert_array_equal(dropped_rows,
                                      np.zeros_like(dropped_rows))


class TestExpertSharding:
    def test_sharded_matches_unsharded(self):
        """Experts sharded over the 'expert' axis (GSPMD all-to-all path)
        must produce the same numbers as the replicated run."""
        mesh = mesh_for_devices(expert=4)  # 8 devices → expert=4 × data=2
        assert EXPERT_AXIS in mesh.axis_names
        x = jax.random.normal(jax.random.PRNGKey(6), (32, D))
        params = init_moe_params(jax.random.PRNGKey(7), d_model=D, d_ff=F,
                                 n_experts=E)
        y_plain, aux_plain = moe_ffn(params, x)

        shardings = moe_param_sharding(params, mesh)
        params_sharded = jax.device_put(params, shardings)
        y_shard, aux_shard = jax.jit(moe_ffn)(params_sharded, x)
        np.testing.assert_allclose(np.asarray(y_shard), np.asarray(y_plain),
                                   rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(float(aux_shard), float(aux_plain),
                                   rtol=1e-5)

    def test_param_sharding_specs(self):
        mesh = mesh_for_devices(expert=4)
        params = init_moe_params(jax.random.PRNGKey(8), d_model=D, d_ff=F,
                                 n_experts=E)
        sh = moe_param_sharding(params, mesh)
        assert sh["wi"].spec == jax.sharding.PartitionSpec(EXPERT_AXIS)
        assert sh["wo"].spec == jax.sharding.PartitionSpec(EXPERT_AXIS)
        assert sh["router"].spec == jax.sharding.PartitionSpec()


class TestTraining:
    def test_grads_flow_and_aux_loss_balances(self):
        x = jax.random.normal(jax.random.PRNGKey(9), (32, D))
        params = init_moe_params(jax.random.PRNGKey(10), d_model=D, d_ff=F,
                                 n_experts=E)

        def loss(p):
            y, aux = moe_ffn(p, x)
            return jnp.mean(y ** 2) + 0.01 * aux

        grads = jax.grad(loss)(params)
        for leaf in jax.tree_util.tree_leaves(grads):
            assert np.isfinite(np.asarray(leaf)).all()
        # Router must receive gradient (through gates and aux loss).
        assert float(jnp.abs(grads["router"]).sum()) > 0.0


class TestTrainerIntegration:
    def test_sharding_for_tree_places_moe_leaves_on_expert_axis(self):
        """The Trainer's sharding rule (mesh.sharding_for_tree) must put
        GPT's expert-stacked weights on the expert axis — otherwise the
        advertised expert parallelism silently replicates."""
        import jax.numpy as jnp

        from cron_operator_tpu.models import GPT, GPTConfig
        from cron_operator_tpu.parallel.mesh import sharding_for_tree

        mesh = mesh_for_devices(expert=4)
        cfg = GPTConfig.tiny(max_len=32, attention_impl="xla",
                             moe_every=2, num_experts=4)
        m = GPT(cfg, mesh=mesh)
        params = m.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 32), jnp.int32))["params"]
        sh = sharding_for_tree(params, mesh)
        moe = sh["layer_1"]["moe"]
        assert moe["wi"].spec == jax.sharding.PartitionSpec(EXPERT_AXIS)
        assert moe["wo"].spec == jax.sharding.PartitionSpec(EXPERT_AXIS)
        # router is rank-2 → falls through to the shape rules (replicated
        # here: no tensor/fsdp axes in this mesh)
        assert EXPERT_AXIS not in (moe["router"].spec or ())

    def test_moe_compute_dtype_follows_model(self):
        """bf16 models must run the expert matmuls in bf16 (MXU path),
        keeping only routing in f32."""
        import jax.numpy as jnp

        params = init_moe_params(jax.random.PRNGKey(0), d_model=D, d_ff=F,
                                 n_experts=E)
        x = jax.random.normal(jax.random.PRNGKey(1), (16, D), jnp.bfloat16)
        y, aux = moe_ffn(params, x, compute_dtype=jnp.bfloat16)
        assert y.dtype == jnp.bfloat16
        assert aux.dtype == jnp.float32
