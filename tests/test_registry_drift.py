"""Registry drift guard: every metric family the package emits must be
declared in ``_FAMILY_META`` (runtime/manager.py), so the exposition
always carries ``# HELP``/``# TYPE`` for it. A new ``inc``/``observe``/
``set`` call site with an undeclared family fails here instead of
shipping a bare, header-less series."""

from __future__ import annotations

import pathlib
import re

import cron_operator_tpu
from cron_operator_tpu.runtime.manager import _FAMILY_META

PKG_ROOT = pathlib.Path(cron_operator_tpu.__file__).parent

# Family = the leading identifier of the first string literal passed to a
# metrics sink call. Receiver-restricted (`metrics.` / the `self._count`
# shim in the reconciler and audit journal / persistence's
# `self._observe` histogram shim) so unrelated `.set()` calls
# (threading.Event etc.) never match; `\s*` spans newlines, catching the
# multi-line 'family' f'{{labels}}' concatenation idiom.
_CALL_RE = re.compile(
    r"(?:metrics\.(?:inc|observe|set)|self\._(?:count|observe))\(\s*"
    r"f?['\"]([A-Za-z_][A-Za-z0-9_]*)"
)

# Interned-series idiom: hot loops pre-format the series once into an
# `s_*` local (manager worker) or `self._s_*` attribute (workqueue) and
# pass the variable to the sink, so the literal never appears inside the
# call parens. The assignment itself carries the family name.
_INTERN_RE = re.compile(
    r"(?:\b|\.)_?s_[a-z_]+\s*=\s*\(?\s*f?['\"]([A-Za-z_][A-Za-z0-9_]*)"
)


def _emitted_families():
    found = {}
    for path in sorted(PKG_ROOT.rglob("*.py")):
        text = path.read_text()
        for regex in (_CALL_RE, _INTERN_RE):
            for m in regex.finditer(text):
                found.setdefault(m.group(1), []).append(
                    f"{path.relative_to(PKG_ROOT.parent)}:"
                    f"{text.count(chr(10), 0, m.start()) + 1}"
                )
    return found


class TestRegistryDrift:
    def test_call_sites_are_found(self):
        """The scan itself must keep working: if a refactor changes the
        call idiom so nothing matches, this fails before the drift check
        silently passes on an empty set."""
        found = _emitted_families()
        assert len(found) >= 10, f"suspiciously few call sites: {found}"
        # spot-check the three sink kinds all get captured
        assert "controller_runtime_reconcile_total" in found      # inc
        assert "controller_runtime_reconcile_time_seconds" in found  # observe
        assert "workqueue_depth" in found                          # set
        # the observability fan-in families: typed cluster events
        # (audit.py) and counted span-ingest drops (telemetry/trace.py)
        assert "cluster_events_total" in found
        assert "trace_spans_dropped_total" in found

    def test_trace_and_event_families_declared_with_types(self):
        """The tracing/fan-in families must stay declared counters so
        ``/metrics`` exposition keeps HELP/TYPE for them and the labeled
        ``reason="ingest"`` / ``event=...`` series inherit headers."""
        for family in ("cluster_events_total", "trace_spans_dropped_total"):
            assert family in _FAMILY_META, family
            mtype, mhelp = _FAMILY_META[family]
            assert mtype == "counter", family
            assert mhelp

    def test_split_families_declared_with_types(self):
        """The live-split observability families: split outcomes and
        latency distributions (shard.py) plus the router's wrong-shard
        retry / probe-fallback counters. All must be scanned AND
        declared so ``/debug/shards`` graphs have headered series."""
        found = _emitted_families()
        expected = {
            "shard_splits_total": "counter",
            "shard_split_duration_seconds": "histogram",
            "shard_split_dark_window_seconds": "histogram",
            "router_wrong_shard_retries_total": "counter",
            "router_probe_fallbacks_total": "counter",
            "wal_fenced_appends_total": "counter",
        }
        for family, want_type in expected.items():
            assert family in found, family
            assert family in _FAMILY_META, family
            mtype, mhelp = _FAMILY_META[family]
            assert mtype == want_type, family
            assert mhelp

    def test_integrity_families_declared_with_types(self):
        """The storage-integrity families (per-record CRC, quarantine,
        degraded mode, scrubber, checkpoint fallback chain) must be
        scanned AND declared: the I12 soak reads these series to prove
        no corrupted record was applied and degraded shards failed
        closed."""
        found = _emitted_families()
        expected = {
            "wal_crc_failures_total": "counter",
            "wal_records_quarantined_total": "counter",
            "storage_degraded": "gauge",
            "wal_degraded_refused_total": "counter",
            "scrub_passes_total": "counter",
            "scrub_records_verified_total": "counter",
            "scrub_corruptions_found_total": "counter",
            "shard_follower_records_rejected_total": "counter",
            "workload_checkpoint_fallbacks_total": "counter",
        }
        for family, want_type in expected.items():
            assert family in found, family
            assert family in _FAMILY_META, family
            mtype, mhelp = _FAMILY_META[family]
            assert mtype == want_type, family
            assert mhelp

    def test_partition_families_declared_with_types(self):
        """The lying-network families (PR 20: injected net faults,
        half-open heartbeat timeouts, duplicate-frame no-ops, retry
        budget denials, follower backoff gauge, clock jumps) must be
        scanned AND declared: the I13 partition soak reads these series
        to prove the schedule bit, detection stayed bounded, and no
        retry storm reached the healthy shards."""
        found = _emitted_families()
        expected = {
            "net_faults_injected_total": "counter",
            "transport_heartbeat_timeouts_total": "counter",
            "transport_duplicate_frames_total": "counter",
            "router_retry_budget_exhausted_total": "counter",
            "shard_follower_reconnect_backoff_seconds": "gauge",
            "cron_clock_jumps_total": "counter",
        }
        for family, want_type in expected.items():
            assert family in found, family
            assert family in _FAMILY_META, family
            mtype, mhelp = _FAMILY_META[family]
            assert mtype == want_type, family
            assert mhelp

    def test_every_emitted_family_is_declared(self):
        undeclared = {
            family: sites
            for family, sites in _emitted_families().items()
            if family not in _FAMILY_META
        }
        assert not undeclared, (
            "metric families emitted but missing from _FAMILY_META "
            f"(runtime/manager.py): {undeclared}"
        )

    def test_declared_types_are_valid(self):
        for family, (mtype, mhelp) in _FAMILY_META.items():
            assert mtype in ("counter", "gauge", "histogram"), family
            assert mhelp, f"{family} has no HELP text"
