"""TPU slice topology + admission injection tests (SURVEY.md §7 step 4b)."""

import pytest

from cron_operator_tpu.backends.tpu import (
    NODESEL_ACCELERATOR,
    NODESEL_TOPOLOGY,
    RESOURCE_TPU,
    TopologyError,
    inject_tpu_topology,
    render_coordinator_env,
    slice_for,
    slice_for_shorthand,
)


class TestSliceResolution:
    @pytest.mark.parametrize(
        "family,topology,chips,hosts,per_host",
        [
            ("v5e", "1x1", 1, 1, 1),
            ("v5e", "2x2", 4, 1, 4),
            ("v5e", "2x4", 8, 1, 8),
            ("v5e", "4x4", 16, 4, 4),
            ("v5e", "4x8", 32, 8, 4),
            ("v5e", "8x8", 64, 16, 4),
            ("v5e", "16x16", 256, 64, 4),
            ("v5p", "2x2x1", 4, 1, 4),
            ("v5p", "2x2x2", 8, 2, 4),
            ("v5p", "2x2x4", 16, 4, 4),
            ("v4", "2x2x2", 8, 2, 4),
            ("v6e", "4x4", 16, 4, 4),
        ],
    )
    def test_shapes(self, family, topology, chips, hosts, per_host):
        s = slice_for(family, topology)
        assert (s.chips, s.hosts, s.chips_per_host) == (chips, hosts, per_host)
        assert s.multi_host == (hosts > 1)

    def test_accelerator_label_roundtrip(self):
        s = slice_for("tpu-v5-lite-podslice", "4x4")
        assert s.accelerator == "tpu-v5-lite-podslice"
        assert s.hosts == 4

    def test_shorthand(self):
        s = slice_for_shorthand("v5e-16")
        assert (s.chips, s.hosts) == (16, 4)
        s = slice_for_shorthand("v5e-64")
        assert (s.chips, s.hosts) == (64, 16)
        s = slice_for_shorthand("v5e-1")
        assert (s.chips, s.hosts) == (1, 1)

    @pytest.mark.parametrize(
        "name,accel,chips,hosts",
        [
            # The fleet-pool shorthands (runtime/fleet.parse_pool feeds
            # these straight to slice_for_shorthand): every chip count a
            # mixed v4/v5p/v6e pool spells must resolve, with the 3D
            # families on 3D topologies and host counts matching the
            # 4-chips/host multi-host rule.
            ("v6e-32", "tpu-v6e-slice", 32, 8),
            ("v5p-4", "tpu-v5p-slice", 4, 1),
            ("v5p-32", "tpu-v5p-slice", 32, 8),
            ("v4-16", "tpu-v4-podslice", 16, 4),
            ("v4-32", "tpu-v4-podslice", 32, 8),
        ],
    )
    def test_fleet_pool_shorthands(self, name, accel, chips, hosts):
        s = slice_for_shorthand(name)
        assert s.accelerator == accel
        assert (s.chips, s.hosts) == (chips, hosts)
        # Shorthand chip count is the product of its topology dims.
        fam, topo = name.split("-")[0], s.topology
        assert s == slice_for(fam, topo)

    def test_shorthand_table_is_self_consistent(self):
        # Every entry resolves, and the advertised chip count in the
        # shorthand name ("v5p-32" -> 32) matches the resolved spec.
        from cron_operator_tpu.backends.tpu import _SHORTHAND

        for name in _SHORTHAND:
            s = slice_for_shorthand(name)
            assert s.chips == int(name.rsplit("-", 1)[1]), name
            assert s.hosts * s.chips_per_host == s.chips, name

    def test_errors(self):
        with pytest.raises(TopologyError):
            slice_for("v9x", "4x4")
        with pytest.raises(TopologyError):
            slice_for("v5e", "4x4x4")  # v5e is 2D
        with pytest.raises(TopologyError):
            slice_for("v5p", "4x4")  # v5p is 3D
        with pytest.raises(TopologyError):
            slice_for("v5e", "bananas")
        with pytest.raises(TopologyError):
            slice_for_shorthand("v5e-3")


def tpu_job(accel="v5e", topo="4x4"):
    return {
        "apiVersion": "kubeflow.org/v1",
        "kind": "JAXJob",
        "metadata": {
            "name": "train",
            "namespace": "default",
            "annotations": {
                "tpu.kubedl.io/accelerator": accel,
                "tpu.kubedl.io/topology": topo,
            },
        },
        "spec": {"replicaSpecs": {"Worker": {"replicas": 1}}},
    }


class TestInjection:
    def test_multi_host_injection(self):
        job = tpu_job("v5e", "4x4")
        spec = inject_tpu_topology(job)
        assert spec is not None and spec.hosts == 4
        worker = job["spec"]["replicaSpecs"]["Worker"]
        # replicas forced to host count (gang: one pod per host)
        assert worker["replicas"] == 4
        pod_spec = worker["template"]["spec"]
        assert pod_spec["nodeSelector"][NODESEL_ACCELERATOR] == "tpu-v5-lite-podslice"
        assert pod_spec["nodeSelector"][NODESEL_TOPOLOGY] == "4x4"
        c = pod_spec["containers"][0]
        assert c["resources"]["requests"][RESOURCE_TPU] == "4"
        assert c["resources"]["limits"][RESOURCE_TPU] == "4"
        env_names = [e["name"] for e in c["env"]]
        assert "JAX_COORDINATOR_ADDRESS" in env_names
        assert "JAX_NUM_PROCESSES" in env_names
        assert job["metadata"]["annotations"]["tpu.kubedl.io/gang-size"] == "4"

    def test_single_host(self):
        job = tpu_job("v5e", "1x1")
        spec = inject_tpu_topology(job)
        assert spec.hosts == 1
        assert job["spec"]["replicaSpecs"]["Worker"]["replicas"] == 1

    def test_non_tpu_job_untouched(self):
        job = {
            "apiVersion": "kubeflow.org/v1",
            "kind": "PyTorchJob",
            "metadata": {"name": "gpu", "namespace": "default"},
            "spec": {"replicaSpecs": {"Worker": {"replicas": 2}}},
        }
        import copy

        before = copy.deepcopy(job)
        assert inject_tpu_topology(job) is None
        assert job == before

    def test_coordinator_env(self):
        spec = slice_for("v5e", "4x4")
        env = render_coordinator_env("train", "ns1", spec)
        addr = next(e for e in env if e["name"] == "JAX_COORDINATOR_ADDRESS")
        assert addr["value"] == "train-worker-0.train.ns1.svc:8476"
        nproc = next(e for e in env if e["name"] == "JAX_NUM_PROCESSES")
        assert nproc["value"] == "4"

    def test_param_env_names_sanitized(self):
        """Annotation keys with '-'/'.' must render to C-identifier env names
        (the kube-apiserver rejects anything else at pod admission) and
        round-trip through the runner's normalization."""
        from cron_operator_tpu.backends.tpu import render_job_env
        from cron_operator_tpu.workloads.runner import _gather_params

        job = {
            "metadata": {
                "name": "j", "namespace": "ns",
                "annotations": {
                    "tpu.kubedl.io/param.checkpoint-dir": "/ckpt",
                    "tpu.kubedl.io/param.lr.schedule": "cosine",
                },
            }
        }
        env = render_job_env(job)
        names = [e["name"] for e in env if e["name"].startswith("TPU_PARAM_")]
        assert names == ["TPU_PARAM_CHECKPOINT_DIR", "TPU_PARAM_LR_SCHEDULE"]
        import re
        for n in names:
            assert re.fullmatch(r"[A-Za-z_][A-Za-z0-9_]*", n)
        # CLI-arg path applies the same normalization.
        params = _gather_params(["checkpoint-dir=/ckpt", "lr.schedule=cosine"])
        assert params == {"checkpoint_dir": "/ckpt", "lr_schedule": "cosine"}
        # Distinct keys that collide after normalization fail loudly
        # instead of silently shadowing (kubelet last-one-wins).
        bad = {
            "metadata": {"name": "j", "annotations": {
                "tpu.kubedl.io/param.lr-schedule": "linear",
                "tpu.kubedl.io/param.lr.schedule": "cosine",
            }}
        }
        import pytest
        with pytest.raises(ValueError, match="normalize"):
            render_job_env(bad)
