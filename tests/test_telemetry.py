"""End-to-end tracing: one trace id from cron tick to first train step.

Covers the telemetry subsystem three ways:

- ``Tracer`` unit behavior (bounded store, record/finish, grouping,
  the ``/debug/traces`` JSON shape),
- propagation plumbing (the controller stamps the workload annotation;
  ``render_job_env`` turns it into the runner env var),
- the ISSUE acceptance e2e: a live stack (real-clock Manager worker
  pool + LocalExecutor + CronReconciler, all sharing one Tracer and one
  Metrics registry) fires a real ``@every`` tick and the resulting
  trace id links reconcile → submit → first_step spans on
  ``/debug/traces`` while ``/metrics`` exposes the controller-runtime
  parity families and the phase decomposition.
"""

from __future__ import annotations

import json
import time
import urllib.request
from datetime import datetime, timedelta, timezone

import pytest

from cron_operator_tpu.api.scheme import GVK_CRON, default_scheme
from cron_operator_tpu.backends.local import LocalExecutor
from cron_operator_tpu.backends.tpu import render_job_env
from cron_operator_tpu.controller import CronReconciler
from cron_operator_tpu.runtime import APIServer, Manager
from cron_operator_tpu.runtime.manager import PROMETHEUS_CONTENT_TYPE
from cron_operator_tpu.telemetry import (
    ANNOTATION_TRACE_ID,
    ENV_TRACE_ID,
    Span,
    Tracer,
    new_trace_id,
)

T0 = datetime(2026, 1, 1, tzinfo=timezone.utc)


class TestTracerUnit:
    def test_record_and_group_by_trace(self):
        tr = Tracer()
        a = tr.record("reconcile", "t-aaaa", start_s=10.0, end_s=10.5)
        tr.record("submit", "t-aaaa", start_s=10.1, end_s=10.4,
                  parent_id=a.span_id)
        tr.record("first_step", "t-bbbb", start_s=20.0, end_s=25.0)

        spans_a = tr.spans("t-aaaa")
        assert [s["name"] for s in spans_a] == ["reconcile", "submit"]
        assert spans_a[1]["parent_id"] == a.span_id
        assert spans_a[0]["duration_s"] == pytest.approx(0.5)

        traces = tr.traces()
        assert [t["trace_id"] for t in traces] == ["t-aaaa", "t-bbbb"]
        # spans within a trace come back sorted by start time
        assert [s["start_s"] for s in traces[0]["spans"]] == [10.0, 10.1]

    def test_span_invisible_until_finished(self):
        tr = Tracer()
        s = tr.start_span("reconcile", "t-cccc", start_s=1.0)
        assert tr.spans() == []
        tr.finish(s, end_s=2.0)
        assert len(tr.spans()) == 1

    def test_store_is_bounded_fifo(self):
        tr = Tracer(max_spans=4)
        for i in range(10):
            tr.record(f"s{i}", "t-dddd", start_s=float(i), end_s=float(i))
        names = [s["name"] for s in tr.spans()]
        assert names == ["s6", "s7", "s8", "s9"]

    def test_duration_clamped_non_negative(self):
        s = Span(name="x", trace_id="t", start_s=5.0, end_s=4.0)
        assert s.duration_s == 0.0

    def test_render_json_shape(self):
        tr = Tracer()
        tr.record("reconcile", "t-eeee", start_s=1.0, end_s=2.0,
                  attrs={"cron": "default/demo"})
        doc = json.loads(tr.render_json())
        (trace,) = doc["traces"]
        assert trace["trace_id"] == "t-eeee"
        (span,) = trace["spans"]
        assert span["name"] == "reconcile"
        assert span["attrs"] == {"cron": "default/demo"}
        assert span["duration_s"] == pytest.approx(1.0)

    def test_trace_ids_are_unique_hex(self):
        ids = {new_trace_id() for _ in range(64)}
        assert len(ids) == 64
        assert all(len(i) == 16 for i in ids)

    def test_spans_dropped_counted_and_metered(self):
        from cron_operator_tpu.runtime.manager import Metrics

        m = Metrics()
        tr = Tracer(max_spans=2)
        tr.instrument(m)
        for i in range(5):
            tr.record(f"s{i}", "t-ffff", start_s=float(i), end_s=float(i))
        assert tr.spans_dropped == 3
        assert m.get("trace_spans_dropped_total") == 3
        # eviction is visible on the served body, never silent
        assert json.loads(tr.render_json())["spans_dropped"] == 3


class TestLineage:
    """Elastic-resume lineage: one trace id spans the whole preempt→
    resume chain, and /debug/traces summarizes productive vs. wasted
    steps per attempt."""

    def test_resume_spans_render_lineage_summary(self):
        tr = Tracer()
        tid = "t-chain"
        tr.record("first_step", tid, start_s=1.0, end_s=2.0)
        tr.record("resume", tid, start_s=10.0, end_s=11.0, attrs={
            "attempt": 1, "workload": "run-r1",
            "resumed_from_step": 100, "pre_steps": 130,
        })
        tr.record("resume", tid, start_s=20.0, end_s=21.0, attrs={
            "attempt": 2, "workload": "run-r2",
            "resumed_from_step": 200, "pre_steps": 220,
        })
        (trace,) = [t for t in tr.traces() if t["trace_id"] == tid]
        lin = trace["lineage"]
        assert lin["attempts"] == 3
        assert [c["attempt"] for c in lin["resumes"]] == [1, 2]
        assert [c["wasted_steps"] for c in lin["resumes"]] == [30, 20]
        assert lin["wasted_steps"] == 50
        # lineage appears on the served JSON too
        served = json.loads(tr.render_json())
        (entry,) = [t for t in served["traces"] if t["trace_id"] == tid]
        assert entry["lineage"]["attempts"] == 3

    def test_trace_without_resumes_has_no_lineage(self):
        tr = Tracer()
        tr.record("reconcile", "t-plain", start_s=1.0, end_s=2.0)
        (trace,) = tr.traces()
        assert "lineage" not in trace

    def test_controller_propagates_root_trace_through_resume(
        self, api, fake_clock
    ):
        """The -r1 successor inherits the ROOT attempt's trace id (no
        fresh id minted), and the reconciler records a resume span with
        the chain's productive/wasted step attrs under that id."""
        from cron_operator_tpu.api.v1alpha1 import LABEL_CRON_NAME
        from cron_operator_tpu.backends.tpu import (
            ANNOTATION_ELASTIC_RESUME,
        )

        tracer = Tracer()
        rec = CronReconciler(api, tracer=tracer)
        cron = _cron(schedule="0 0 1 1 *")  # no tick due
        api.create(cron)
        api.create({
            "apiVersion": "kubeflow.org/v1", "kind": "JAXJob",
            "metadata": {
                "name": "demo-run", "namespace": "default",
                "labels": {LABEL_CRON_NAME: "demo"},
                "annotations": {
                    ANNOTATION_ELASTIC_RESUME: "true",
                    ANNOTATION_TRACE_ID: "feed0123deadbeef",
                },
            },
            "spec": {"replicaSpecs": {"Worker": {"replicas": 8}}},
        })
        api.patch_status("kubeflow.org/v1", "JAXJob", "default",
                         "demo-run", {
                             "conditions": [
                                 {"type": "Preempted", "status": "True",
                                  "reason": "TPUSlicePreempted"},
                                 {"type": "Failed", "status": "True",
                                  "reason": "TPUSlicePreempted"},
                             ],
                             "preemption": {"survivingDevices": 4,
                                            "priorDevices": 8},
                             "trainingProgress": {"steps_done": 130},
                         })
        rec.reconcile("default", "demo")

        successor = api.get("kubeflow.org/v1", "JAXJob", "default",
                            "demo-run-r1")
        ann = successor["metadata"]["annotations"]
        assert ann[ANNOTATION_TRACE_ID] == "feed0123deadbeef"

        # successor starts training from its checkpoint; the next sweep
        # records the resume span under the inherited trace id
        api.patch_status("kubeflow.org/v1", "JAXJob", "default",
                         "demo-run-r1", {"trainingProgress": {
                             "resumed_from_step": 100,
                             "steps_done": 105,
                         }})
        rec.reconcile("default", "demo")

        spans = tracer.spans("feed0123deadbeef")
        (resume,) = [s for s in spans if s["name"] == "resume"]
        assert resume["attrs"]["attempt"] == 1
        assert resume["attrs"]["workload"] == "demo-run-r1"
        assert resume["attrs"]["resumed_from_step"] == 100
        assert resume["attrs"]["pre_steps"] == 130
        assert resume["attrs"]["wasted_steps"] == 30
        (trace,) = [t for t in tracer.traces()
                    if t["trace_id"] == "feed0123deadbeef"]
        assert trace["lineage"]["wasted_steps"] == 30


def _cron(name="demo", schedule="*/5 * * * *"):
    return {
        "apiVersion": "apps.kubedl.io/v1alpha1",
        "kind": "Cron",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {
            "schedule": schedule,
            "template": {
                "workload": {
                    "apiVersion": "kubeflow.org/v1",
                    "kind": "JAXJob",
                    "spec": {"replicaSpecs": {"Worker": {"replicas": 1}}},
                }
            },
        },
    }


class TestPropagation:
    def test_tick_stamps_trace_annotation_and_records_spans(
        self, api, fake_clock
    ):
        tracer = Tracer()
        rec = CronReconciler(api, tracer=tracer)
        api.create(_cron())
        fake_clock.advance(timedelta(minutes=10))
        rec.reconcile("default", "demo")

        jobs = api.list("kubeflow.org/v1", "JAXJob", namespace="default")
        assert len(jobs) == 1
        ann = jobs[0]["metadata"]["annotations"]
        trace_id = ann.get(ANNOTATION_TRACE_ID)
        assert trace_id

        spans = tracer.spans(trace_id)
        by_name = {s["name"]: s for s in spans}
        assert set(by_name) == {"reconcile", "submit"}
        # submit is a child of reconcile in the same trace
        assert by_name["submit"]["parent_id"] == by_name["reconcile"]["span_id"]
        assert by_name["reconcile"]["attrs"]["cron"] == "default/demo"

    def test_each_tick_gets_a_fresh_trace_id(self, api, fake_clock):
        rec = CronReconciler(api, tracer=Tracer())
        api.create(_cron())
        seen = set()
        for _ in range(3):
            fake_clock.advance(timedelta(minutes=5))
            rec.reconcile("default", "demo")
        for job in api.list("kubeflow.org/v1", "JAXJob", namespace="default"):
            seen.add(job["metadata"]["annotations"][ANNOTATION_TRACE_ID])
        assert len(seen) == 3

    def test_annotation_stamped_even_without_tracer(self, api, fake_clock):
        rec = CronReconciler(api)  # no tracer wired
        api.create(_cron())
        fake_clock.advance(timedelta(minutes=5))
        rec.reconcile("default", "demo")
        (job,) = api.list("kubeflow.org/v1", "JAXJob", namespace="default")
        assert job["metadata"]["annotations"][ANNOTATION_TRACE_ID]

    def test_render_job_env_carries_trace_id(self):
        job = {
            "apiVersion": "kubeflow.org/v1",
            "kind": "JAXJob",
            "metadata": {
                "name": "j", "namespace": "default",
                "annotations": {ANNOTATION_TRACE_ID: "cafe0123deadbeef"},
            },
            "spec": {"replicaSpecs": {"Worker": {"replicas": 1}}},
        }
        env = {e["name"]: e.get("value") for e in render_job_env(job)}
        assert env[ENV_TRACE_ID] == "cafe0123deadbeef"

    def test_render_job_env_omits_var_when_unannotated(self):
        job = {
            "apiVersion": "kubeflow.org/v1",
            "kind": "JAXJob",
            "metadata": {"name": "j", "namespace": "default"},
            "spec": {"replicaSpecs": {"Worker": {"replicas": 1}}},
        }
        names = {e["name"] for e in render_job_env(job)}
        assert ENV_TRACE_ID not in names


class TestEndToEndTrace:
    """The ISSUE acceptance: one cron tick through the live stack, one
    trace id linking the tick's spans, parity families on /metrics."""

    @pytest.fixture()
    def stack(self):
        api = APIServer()  # real clock: the executor runs real sleeps
        mgr = Manager(api, max_concurrent_reconciles=10)
        tracer = Tracer()
        rec = CronReconciler(api, metrics=mgr.metrics, tracer=tracer)
        mgr.add_controller(
            "cron", rec.reconcile, for_gvk=GVK_CRON,
            owns=default_scheme().workload_kinds(),
        )
        ex = LocalExecutor(api, metrics=mgr.metrics, tracer=tracer)
        ex.start()
        mgr.start()
        try:
            yield api, mgr, tracer
        finally:
            mgr.stop()
            ex.stop()
            api.close()

    def _fire_one_tick(self, api, mgr, tracer):
        cron = _cron(schedule="@every 1s")
        # Simulated workloads report first_step_at/started_at immediately,
        # feeding the same telemetry path real training does.
        cron["spec"]["template"]["workload"]["metadata"] = {
            "annotations": {"tpu.kubedl.io/simulate-duration": "100ms"}
        }
        api.create(cron)

        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            for trace in tracer.traces():
                names = {s["name"] for s in trace["spans"]}
                if {"reconcile", "submit", "first_step"} <= names:
                    return trace
            time.sleep(0.05)
        raise AssertionError(
            f"no complete trace within deadline; have {tracer.traces()!r}"
        )

    def test_single_trace_id_links_tick_to_first_step(self, stack):
        api, mgr, tracer = stack
        trace = self._fire_one_tick(api, mgr, tracer)

        tid = trace["trace_id"]
        spans = {s["name"]: s for s in trace["spans"]}
        assert all(s["trace_id"] == tid for s in trace["spans"])
        assert spans["submit"]["parent_id"] == spans["reconcile"]["span_id"]

        # The annotation on the created workload is the same trace id.
        jobs = [
            j for j in api.list("kubeflow.org/v1", "JAXJob",
                                namespace="default")
            if (j["metadata"].get("annotations") or {})
               .get(ANNOTATION_TRACE_ID) == tid
        ]
        assert len(jobs) == 1
        # first_step attrs point back at that workload
        assert (spans["first_step"]["attrs"]["workload"]
                == jobs[0]["metadata"]["name"])

        # Spans are wall-clock ordered: the tick precedes the first step.
        assert spans["reconcile"]["start_s"] <= spans["first_step"]["end_s"]

        served = json.loads(tracer.render_json())
        assert any(t["trace_id"] == tid for t in served["traces"])

    def test_metrics_endpoint_has_parity_families_and_phases(self, stack):
        api, mgr, tracer = stack
        self._fire_one_tick(api, mgr, tracer)

        from cron_operator_tpu.cli.main import _serve

        server = _serve(
            0,
            {
                "/metrics": lambda: (mgr.metrics.render_prometheus(),
                                     PROMETHEUS_CONTENT_TYPE),
                "/debug/traces": lambda: (tracer.render_json(),
                                          "application/json"),
            },
            "test-telemetry",
        )
        try:
            port = server.server_address[1]
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=5
            ) as resp:
                assert (resp.headers["Content-Type"]
                        == PROMETHEUS_CONTENT_TYPE)
                body = resp.read().decode()
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/traces", timeout=5
            ) as resp:
                assert resp.headers["Content-Type"] == "application/json"
                traces = json.loads(resp.read().decode())["traces"]
        finally:
            server.shutdown()

        # controller-runtime parity families, headers included.
        for family in (
            "controller_runtime_reconcile_time_seconds",
            "workqueue_depth",
            "workqueue_adds_total",
            "workqueue_queue_duration_seconds",
        ):
            assert f"# HELP {family} " in body
            assert f"# TYPE {family} " in body
        assert ('controller_runtime_reconcile_time_seconds_bucket'
                '{controller="cron",le=' in body)
        assert 'workqueue_depth{name="cron"}' in body
        assert 'workqueue_queue_duration_seconds_bucket{le=' in body \
            or 'workqueue_queue_duration_seconds_bucket{name="cron",le=' \
               in body

        # tick→first-step decomposed into phase components.
        assert "# TYPE cron_tick_phase_seconds histogram" in body
        assert 'cron_tick_phase_seconds_bucket{phase="queue",le=' in body
        assert 'cron_tick_phase_seconds_bucket{phase="first_step",le=' in body

        # the traces body served next to /metrics carries complete traces
        assert any(
            {"reconcile", "submit", "first_step"}
            <= {s["name"] for s in t["spans"]}
            for t in traces
        )
