"""Chaos-layer specs: the seeded fault injector, the conflict-retry
helper, and the Manager's watch-resync hardening.

The determinism contract under test is the one ``hack/chaos_soak.py``
relies on: a :class:`FaultPlan`'s schedule expansion, trace hash, and
per-call-index decisions are pure functions of the seed — never of
wall-clock time, thread interleaving, or call order across verbs."""

from datetime import timedelta

import pytest

from cron_operator_tpu.api.v1alpha1 import (
    API_VERSION,
    KIND_CRON,
    parse_time,
    rfc3339,
)
from cron_operator_tpu.controller.cron_controller import (
    SUBMIT_ATTEMPTS,
    CronReconciler,
)
from cron_operator_tpu.runtime.faults import (
    FaultInjector,
    FaultPlan,
    seeded_fraction,
)
from cron_operator_tpu.runtime.kube import (
    ApiError,
    ConflictError,
    ServerTimeoutError,
)
from cron_operator_tpu.runtime.manager import (
    LEADER_LEASE_NAME,
    LEASE_API_VERSION,
    LEASE_KIND,
    Manager,
    Metrics,
)
from cron_operator_tpu.runtime.retry import with_conflict_retry

JAX_AV, JAX_KIND = "kubeflow.org/v1", "JAXJob"


def make_cron(api, name="demo", schedule="*/1 * * * *"):
    return api.create({
        "apiVersion": API_VERSION,
        "kind": KIND_CRON,
        "metadata": {"name": name, "namespace": "default"},
        "spec": {
            "schedule": schedule,
            "template": {"workload": {
                "apiVersion": JAX_AV,
                "kind": JAX_KIND,
                "metadata": {},
                "spec": {"replicaSpecs": {"Worker": {"replicas": 1}}},
            }},
        },
    })


def make_job(api, name="job-0"):
    return api.create({
        "apiVersion": JAX_AV,
        "kind": JAX_KIND,
        "metadata": {"name": name, "namespace": "default"},
        "spec": {"replicaSpecs": {"Worker": {"replicas": 1}}},
    })


# ---------------------------------------------------------------------------
# FaultPlan: PRF + schedule determinism
# ---------------------------------------------------------------------------


class TestFaultPlan:
    def test_seeded_fraction_is_a_pure_function(self):
        a = seeded_fraction(7, "latency", "update", 3)
        b = seeded_fraction(7, "latency", "update", 3)
        assert a == b
        assert 0.0 <= a < 1.0
        # distinct injection points decide independently
        assert a != seeded_fraction(7, "latency", "update", 4)
        assert a != seeded_fraction(8, "latency", "update", 3)

    def test_schedule_expansion_is_deterministic(self):
        rounds = 50
        s1 = FaultPlan.default_chaos(3).schedule(rounds)
        s2 = FaultPlan.default_chaos(3).schedule(rounds)
        assert s1 == s2
        assert FaultPlan.default_chaos(3).trace_hash(rounds) == \
            FaultPlan.default_chaos(3).trace_hash(rounds)
        # with 50 rounds at the default probabilities every scheduled
        # fault class appears, and a different seed gives a different trace
        kinds = {e["fault"] for e in s1}
        assert kinds == {"watch_break", "leader_revoke", "preempt_storm"}
        assert FaultPlan.default_chaos(4).trace_hash(rounds) != \
            FaultPlan.default_chaos(3).trace_hash(rounds)

    def test_quiet_plan_schedules_nothing(self):
        assert FaultPlan.quiet(3).schedule(100) == []

    def test_planned_submit_failures_bounded_and_deterministic(self):
        plan = FaultPlan(seed=1, submit_fail_prob=0.5, submit_fail_max=3)
        names = [f"wl-{i}" for i in range(200)]
        planned = [plan.planned_submit_failures(n) for n in names]
        assert planned == [plan.planned_submit_failures(n) for n in names]
        assert all(0 <= p <= 3 for p in planned)
        assert any(p == 0 for p in planned) and any(p > 0 for p in planned)


# ---------------------------------------------------------------------------
# FaultInjector: per-call faults, bounded submit failures, forwarding
# ---------------------------------------------------------------------------


class TestFaultInjector:
    def test_conflict_injection_on_update(self, api):
        inj = FaultInjector(api, FaultPlan(seed=0, conflict_prob=1.0))
        obj = make_job(inj)
        with pytest.raises(ConflictError):
            inj.update(dict(obj))
        assert inj.fault_counts() == {"conflict": 1}

    def test_transient_injection_on_create(self, api):
        inj = FaultInjector(api, FaultPlan(seed=0, transient_prob=1.0))
        with pytest.raises(ServerTimeoutError):
            make_cron(inj)
        assert inj.fault_counts() == {"transient": 1}

    def test_reads_are_never_failed(self, api):
        make_job(api)
        inj = FaultInjector(
            api, FaultPlan(seed=0, conflict_prob=1.0, transient_prob=1.0)
        )
        assert len(inj.list(JAX_AV, JAX_KIND, namespace="default")) == 1
        assert inj.get(JAX_AV, JAX_KIND, "default", "job-0")

    def test_disarm_stops_injection(self, api):
        inj = FaultInjector(api, FaultPlan(seed=0, transient_prob=1.0))
        inj.disarm()
        make_cron(inj)
        assert inj.fault_counts() == {}
        inj.arm()
        with pytest.raises(ServerTimeoutError):
            make_job(inj, "other")

    def test_faults_injected_total_metric(self, api):
        inj = FaultInjector(api, FaultPlan(seed=0, conflict_prob=1.0))
        metrics = Metrics()
        inj.instrument(metrics)
        obj = make_job(inj)
        with pytest.raises(ConflictError):
            inj.update(dict(obj))
        assert metrics.counters['faults_injected_total{kind="conflict"}'] == 1.0

    def test_submit_failures_bounded_per_name(self, api):
        # Every workload name selected, at most 3 failures each: the 4th
        # create of the same name must reach the store.
        plan = FaultPlan(seed=5, submit_fail_prob=1.0, submit_fail_max=3)
        inj = FaultInjector(api, plan)
        planned = plan.planned_submit_failures("job-0")
        assert 1 <= planned <= 3
        failures = 0
        for _ in range(planned):
            with pytest.raises(ServerTimeoutError):
                make_job(inj)
            failures += 1
        made = make_job(inj)  # budget spent — goes through
        assert made["metadata"]["name"] == "job-0"
        assert failures == planned
        assert inj.fault_counts()["submit_fail"] == planned

    def test_non_workload_creates_skip_submit_faults(self, api):
        inj = FaultInjector(
            api, FaultPlan(seed=5, submit_fail_prob=1.0, submit_fail_max=3)
        )
        make_cron(inj)  # Cron is not a SUBMIT_KIND
        assert inj.fault_counts() == {}

    def test_forwarding_preserves_store_surface(self, api):
        inj = FaultInjector(api, FaultPlan.quiet(0))
        make_job(inj)
        assert len(inj) == len(api)
        assert inj.clock is api.clock
        assert inj.events() == []
        assert bool(inj)

    def test_watch_break_drops_events_and_repair_resumes(self, api):
        inj = FaultInjector(api, FaultPlan.quiet(0))
        frames = []
        inj.add_watcher(frames.append)
        make_job(inj, "before")
        api.flush(timeout=2.0)
        assert [f.type for f in frames] == ["ADDED"]

        inj.break_watches()
        make_job(inj, "during")
        api.flush(timeout=2.0)
        assert [f.type for f in frames] == ["ADDED", "ERROR"]
        assert inj.dropped_events() >= 1

        inj.repair_watches()
        make_job(inj, "after")
        api.flush(timeout=2.0)
        types = [f.type for f in frames]
        assert types[:3] == ["ADDED", "ERROR", "BOOKMARK"]
        assert types[-1] == "ADDED"
        names = [
            (f.object.get("metadata") or {}).get("name")
            for f in frames if f.type == "ADDED"
        ]
        assert names == ["before", "after"]  # "during" was dropped

    def test_leadership_revoke_and_expire(self, api, fake_clock):
        inj = FaultInjector(api, FaultPlan.quiet(0))
        assert inj.revoke_leader() is False  # no lease yet
        api.create({
            "apiVersion": LEASE_API_VERSION,
            "kind": LEASE_KIND,
            "metadata": {
                "namespace": "kube-system", "name": LEADER_LEASE_NAME,
            },
            "spec": {
                "holderIdentity": "manager-0",
                "renewTime": rfc3339(fake_clock.now()),
                "leaseDurationSeconds": 15,
            },
        })
        assert inj.revoke_leader() is True
        lease = api.get(
            LEASE_API_VERSION, LEASE_KIND, "kube-system", LEADER_LEASE_NAME
        )
        assert lease["spec"]["holderIdentity"] == "chaos-rival"

        assert inj.expire_leader_lease() is True
        lease = api.get(
            LEASE_API_VERSION, LEASE_KIND, "kube-system", LEADER_LEASE_NAME
        )
        renew = parse_time(lease["spec"]["renewTime"])
        # rewound ≥ 10× the lease duration: any holder reads as expired
        assert fake_clock.now() - renew >= timedelta(seconds=150)


# ---------------------------------------------------------------------------
# with_conflict_retry
# ---------------------------------------------------------------------------


class TestWithConflictRetry:
    def test_succeeds_after_transient_conflicts(self):
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise ConflictError("stale rv")
            return "ok"

        assert with_conflict_retry(flaky, attempts=5, base_s=0.0) == "ok"
        assert calls["n"] == 3

    def test_retries_server_timeout_too(self):
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] == 1:
                raise ServerTimeoutError("503")
            return calls["n"]

        assert with_conflict_retry(flaky, attempts=2, base_s=0.0) == 2

    def test_exhaustion_reraises_last_error(self):
        def always():
            raise ConflictError("never converges")

        with pytest.raises(ConflictError):
            with_conflict_retry(always, attempts=3, base_s=0.0)

    def test_non_retriable_raises_immediately(self):
        calls = {"n": 0}

        def broken():
            calls["n"] += 1
            raise ApiError("schema rejected")

        with pytest.raises(ApiError):
            with_conflict_retry(broken, attempts=5, base_s=0.0)
        assert calls["n"] == 1

    def test_attempts_must_be_positive(self):
        with pytest.raises(ValueError):
            with_conflict_retry(lambda: None, attempts=0)


# ---------------------------------------------------------------------------
# Manager hardening: ERROR degrades readyz, BOOKMARK resyncs
# ---------------------------------------------------------------------------


def _drain(mgr, api, timeout_s=5.0):
    import time as _t
    deadline = _t.monotonic() + timeout_s
    while _t.monotonic() < deadline:
        api.flush(timeout=1.0)
        if all(c.queue.stats()[:2] == (0, 0) for c in mgr._controllers):
            return
        _t.sleep(0.01)


class TestManagerWatchResync:
    def _started_manager(self, api):
        from cron_operator_tpu.api.scheme import GVK_CRON, default_scheme

        rec = CronReconciler(api)
        mgr = Manager(api, max_concurrent_reconciles=2)
        mgr.add_controller(
            "cron", rec.reconcile, for_gvk=GVK_CRON,
            owns=default_scheme().workload_kinds(),
        )
        mgr.start()
        return mgr

    def test_error_frame_degrades_readyz(self, api):
        inj = FaultInjector(api, FaultPlan.quiet(0))
        mgr = self._started_manager(inj)
        try:
            assert mgr.readyz()
            inj.break_watches()
            api.flush(timeout=2.0)
            assert not mgr.readyz()
            assert mgr.healthz()  # degraded, not dead
        finally:
            mgr.stop()

    def test_bookmark_resyncs_and_restores_readyz(self, api, fake_clock):
        inj = FaultInjector(api, FaultPlan.quiet(0))
        mgr = self._started_manager(inj)
        try:
            make_cron(inj)
            _drain(mgr, api)
            inj.break_watches()
            api.flush(timeout=2.0)
            # Edit made while the stream is down: the tick comes due but
            # no MODIFIED/ADDED event reaches the manager.
            fake_clock.advance(timedelta(minutes=2))
            assert not mgr.readyz()

            inj.repair_watches()
            _drain(mgr, api)
            assert mgr.readyz()
            assert mgr.metrics.counters["watch_resyncs_total"] == 1.0
            # The resync's enqueue-all sweep reconciled the due tick.
            assert len(api.list(JAX_AV, JAX_KIND, namespace="default")) == 1
        finally:
            mgr.stop()

    def test_resync_opt_out_keeps_prepr_behavior(self, api):
        inj = FaultInjector(api, FaultPlan.quiet(0))
        mgr = self._started_manager(inj)
        mgr.resync_on_watch_error = False
        try:
            inj.break_watches()
            inj.repair_watches()
            api.flush(timeout=2.0)
            assert not mgr.readyz()  # BOOKMARK ignored: stays degraded
            assert "watch_resyncs_total" not in mgr.metrics.counters
        finally:
            mgr.stop()


# ---------------------------------------------------------------------------
# Reconciler submit retries
# ---------------------------------------------------------------------------


class _AlwaysFailSubmit:
    """API wrapper whose workload creates always time out."""

    def __init__(self, inner):
        self.inner = inner
        self.clock = inner.clock
        self.creates = 0

    def create(self, obj):
        if obj.get("kind") == JAX_KIND:
            self.creates += 1
            raise ServerTimeoutError("injected: backend submit down")
        return self.inner.create(obj)

    def __getattr__(self, name):
        return getattr(self.inner, name)


class TestSubmitRetries:
    def test_bounded_submit_failures_are_retried_through(self, api, fake_clock):
        # Planned failures (≤3) stay below SUBMIT_ATTEMPTS (6): the
        # reconciler's retry loop always gets the workload through.
        inj = FaultInjector(
            api, FaultPlan(seed=5, submit_fail_prob=1.0, submit_fail_max=3)
        )
        metrics = Metrics()
        rec = CronReconciler(inj, metrics=metrics)
        make_cron(inj)
        fake_clock.advance(timedelta(minutes=2))
        rec.reconcile("default", "demo")
        assert len(api.list(JAX_AV, JAX_KIND, namespace="default")) == 1
        assert metrics.counters["cron_submit_retries_total"] >= 1.0
        assert api.events(reason="SubmitRetriesExhausted") == []

    def test_exhaustion_records_warning_event_and_raises(self, api, fake_clock):
        wrapped = _AlwaysFailSubmit(api)
        rec = CronReconciler(wrapped)
        make_cron(api)
        fake_clock.advance(timedelta(minutes=2))
        with pytest.raises(ServerTimeoutError):
            rec.reconcile("default", "demo")
        assert wrapped.creates == SUBMIT_ATTEMPTS
        events = api.events(reason="SubmitRetriesExhausted")
        assert len(events) == 1
        assert events[0].type == "Warning"
        assert "demo-" in events[0].message


class TestDiskFaultInjector:
    """Seeded disk-fault source (I12 harness): deterministic kind
    choice, pop-once errno arming, and JSON-preserving offline
    corruption."""

    def test_choose_kind_is_deterministic_and_covers_all_kinds(self):
        from cron_operator_tpu.runtime.faults import (
            DISK_FAULT_KINDS,
            DiskFaultInjector,
        )

        seen = set()
        for r in range(64):
            a = DiskFaultInjector.choose_kind(42, r)
            b = DiskFaultInjector.choose_kind(42, r)
            assert a == b and a in DISK_FAULT_KINDS
            seen.add(a)
        assert seen == set(DISK_FAULT_KINDS)
        # a different seed produces a different schedule
        sched_a = [DiskFaultInjector.choose_kind(1, r) for r in range(16)]
        sched_b = [DiskFaultInjector.choose_kind(2, r) for r in range(16)]
        assert sched_a != sched_b

    def test_arm_errno_pops_exactly_count_times(self):
        import errno

        from cron_operator_tpu.runtime.faults import DiskFaultInjector

        inj = DiskFaultInjector(seed=7)
        inj.arm_errno("append", errno.EIO, count=2)
        e1 = inj.check("append")
        e2 = inj.check("append")
        assert e1 is not None and e1.errno == errno.EIO
        assert e2 is not None and e2.errno == errno.EIO
        assert inj.check("append") is None
        assert inj.check("fsync") is None  # other ops unaffected
        assert len(inj.injected) == 2

    def test_arm_planned_maps_kinds_to_ops(self):
        import errno

        from cron_operator_tpu.runtime.faults import (
            DISK_FAULT_KINDS,
            DiskFaultInjector,
        )

        ops = {}
        for r in range(64):
            inj = DiskFaultInjector(seed=42, round_idx=r)
            ops[inj.kind] = inj.arm_planned()
        assert ops["eio_append"] == "append"
        assert ops["enospc_append"] == "append"
        assert ops["eio_fsync"] == "fsync"
        assert ops["eio_rename"] == "rename"
        # offline kinds arm nothing — the harness applies them between
        # rounds by mutating the closed segment
        assert ops["bit_flip"] is None
        assert ops["torn_midfile"] is None
        assert set(ops) == set(DISK_FAULT_KINDS)

    def test_flip_value_digit_is_silent_json_loud_crc(self, tmp_path):
        import json

        from cron_operator_tpu.runtime.faults import DiskFaultInjector
        from cron_operator_tpu.runtime.persistence import (
            stamp_crc,
            verify_line,
        )

        path = str(tmp_path / "seg.jsonl")
        lines = [
            stamp_crc(json.dumps(
                {"op": "put", "rv": 100 + i,
                 "obj": {"value": 123456 + i}}).encode())
            for i in range(5)
        ]
        with open(path, "wb") as f:
            f.write(b"\n".join(lines) + b"\n")
        inj = DiskFaultInjector(seed=3)
        offset = inj.flip_value_digit(path)
        assert offset is not None
        with open(path, "rb") as f:
            damaged = f.read().splitlines()
        flipped = [
            (i, line) for i, line in enumerate(damaged)
            if line != lines[i]
        ]
        assert len(flipped) == 1
        _, bad = flipped[0]
        json.loads(bad)  # still VALID JSON — silent without a checksum
        ok, expected, actual = verify_line(bad)
        assert not ok and expected != actual  # ...but the CRC catches it

    def test_flip_never_lands_in_the_crc_stamp(self, tmp_path):
        import json

        from cron_operator_tpu.runtime.faults import DiskFaultInjector
        from cron_operator_tpu.runtime.persistence import (
            split_crc,
            stamp_crc,
        )

        path = str(tmp_path / "seg.jsonl")
        body = json.dumps({"op": "put", "rv": 7, "obj": {"n": 9}}).encode()
        line = stamp_crc(body)
        with open(path, "wb") as f:
            f.write(line + b"\n")
        # every seed must flip inside the VALUE region, never the stamp
        for seed in range(20):
            with open(path, "wb") as f:
                f.write(line + b"\n")
            offset = DiskFaultInjector(seed=seed).flip_value_digit(path)
            assert offset is not None
            assert offset < len(body) - 1  # strictly before the splice
            with open(path, "rb") as f:
                _, crc = split_crc(f.read().splitlines()[0])
            assert crc is not None  # the stamp itself survived intact

    def test_tear_midfile_merges_a_record_into_its_successor(self, tmp_path):
        import json

        from cron_operator_tpu.runtime.faults import DiskFaultInjector

        path = str(tmp_path / "seg.jsonl")
        lines = [
            json.dumps({"op": "put", "rv": i, "obj": {"i": i}}).encode()
            for i in range(6)
        ]
        with open(path, "wb") as f:
            f.write(b"\n".join(lines) + b"\n")
        inj = DiskFaultInjector(seed=5)
        cut = inj.tear_midfile(path)
        assert cut is not None
        with open(path, "rb") as f:
            damaged = f.read().splitlines()
        # one record lost its tail and merged into its successor
        assert len(damaged) == len(lines) - 1
        bad = [l for l in damaged if l not in lines]
        assert len(bad) == 1
        with pytest.raises(ValueError):
            json.loads(bad[0])

    def test_tear_requires_two_records(self, tmp_path):
        from cron_operator_tpu.runtime.faults import DiskFaultInjector

        path = str(tmp_path / "seg.jsonl")
        with open(path, "wb") as f:
            f.write(b'{"op":"put","rv":1}\n')
        assert DiskFaultInjector(seed=5).tear_midfile(path) is None
