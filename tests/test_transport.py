"""Multi-process control plane transport (runtime/transport.py).

Covers the socket/file analogs of the in-process seams:

- length-framed WAL shipping: a record split across TCP segments is
  reassembled whole, and a frame torn by the peer's death is discarded
  whole — the follower can never apply a partial record (invariant I6's
  socket leg);
- reconnect with re-bootstrap: a follower that loses its leader redials
  with bounded backoff and re-seeds from the leader's durable state, so
  no record is missed or double-applied across the drop;
- the on-disk lease: heartbeat renewal, expiry detection, generation
  increments, and the arm-only-after-fresh rule that keeps a standby
  from promoting into a leader that is still booting;
- the router's ShardClient surface parity (list_with_rv, get_frozen,
  barrier no-ops) over a real HTTP front door.
"""

import json
import os
import shutil
import socket
import tempfile
import threading
import time
import unittest

from cron_operator_tpu.runtime.kube import APIServer
from cron_operator_tpu.runtime.persistence import FencedError, Persistence
from cron_operator_tpu.runtime.shard import FollowerReplica, canonical_state
from cron_operator_tpu.runtime.transport import (
    BREAKER_CLOSED,
    BREAKER_HALF_OPEN,
    BREAKER_OPEN,
    FRAME_BOOT,
    FRAME_WAL,
    CircuitBreaker,
    LeaseFile,
    ShardClient,
    ShipFollower,
    WALShipServer,
    decode_bootstrap,
    encode_bootstrap,
    read_frame,
    write_frame,
)
from cron_operator_tpu.utils.clock import FakeClock, RealClock

WORKLOAD_API_VERSION = "kubeflow.org/v1"
WORKLOAD_KIND = "JAXJob"


def _obj(name: str, ns: str = "default") -> dict:
    return {
        "apiVersion": WORKLOAD_API_VERSION,
        "kind": WORKLOAD_KIND,
        "metadata": {"name": name, "namespace": ns},
        "spec": {"replicaSpecs": {"Worker": {"replicas": 1}}},
    }


def _wait(predicate, timeout=5.0, interval=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return predicate()


class _TmpDirTest(unittest.TestCase):
    def setUp(self):
        self.dir = tempfile.mkdtemp(prefix="transport-test-")
        self.addCleanup(shutil.rmtree, self.dir, ignore_errors=True)


class TestFraming(unittest.TestCase):
    def _pair(self):
        a, b = socket.socketpair()
        self.addCleanup(a.close)
        self.addCleanup(b.close)
        return a, b

    def test_round_trip_multiple_frames(self):
        a, b = self._pair()
        payloads = [b"", b"x", b'{"op":"put"}\n' * 100, os.urandom(4096)]
        for p in payloads:
            write_frame(a, FRAME_WAL, p)
        write_frame(a, FRAME_BOOT, b"boot")
        for p in payloads:
            self.assertEqual(read_frame(b), (FRAME_WAL, p, 0))
        self.assertEqual(read_frame(b), (FRAME_BOOT, b"boot", 0))

    def test_seq_travels_with_frame(self):
        a, b = self._pair()
        write_frame(a, FRAME_WAL, b"rec", seq=7)
        self.assertEqual(read_frame(b), (FRAME_WAL, b"rec", 7))

    def test_eof_returns_none(self):
        a, b = self._pair()
        a.close()
        self.assertIsNone(read_frame(b))

    def test_torn_header_discarded_whole(self):
        a, b = self._pair()
        a.sendall(b"W\x00\x00")  # 3 of 9 header bytes, then death
        a.close()
        self.assertIsNone(read_frame(b))

    def test_torn_payload_discarded_whole(self):
        a, b = self._pair()
        import struct
        a.sendall(struct.pack("!cI", FRAME_WAL, 100) + b"only-part")
        a.close()
        # The reader must NOT hand back 9 bytes of a 100-byte record.
        self.assertIsNone(read_frame(b))

    def test_segmented_frame_reassembled(self):
        # One frame dribbled byte-by-byte (worst-case TCP segmentation)
        # still arrives as exactly one payload.
        a, b = self._pair()
        payload = b'{"op":"put","rv":7}\n'
        import struct
        from cron_operator_tpu.runtime.persistence import wal_crc
        wire = (
            struct.pack(
                "!cIII", FRAME_WAL, len(payload), wal_crc(payload), 3)
            + payload
        )
        got = {}

        def reader():
            got["frame"] = read_frame(b)

        t = threading.Thread(target=reader)
        t.start()
        for i in range(len(wire)):
            a.sendall(wire[i:i + 1])
            time.sleep(0.0005)
        t.join(timeout=5)
        self.assertEqual(got["frame"], (FRAME_WAL, payload, 3))

    def test_bootstrap_codec_round_trip(self):
        store = APIServer(clock=FakeClock())
        store.create(_obj("w-0"))
        store.create(_obj("w-1"))
        store.delete(WORKLOAD_API_VERSION, WORKLOAD_KIND, "default", "w-1")
        from cron_operator_tpu.runtime.persistence import RecoveredState
        state = RecoveredState(
            objects=store.all_objects(), rv=int(store._rv),
            wal_records_replayed=3,
        )
        state.wal_deleted_keys = [
            (WORKLOAD_API_VERSION, WORKLOAD_KIND, "default", "w-1")
        ]
        out = decode_bootstrap(encode_bootstrap(state))
        self.assertEqual(out.rv, state.rv)
        self.assertEqual(
            canonical_state(out.objects, out.rv),
            canonical_state(state.objects, state.rv),
        )
        self.assertEqual(out.wal_deleted_keys, state.wal_deleted_keys)


class TestShipSocket(_TmpDirTest):
    """Leader Persistence → WALShipServer → socket → ShipFollower."""

    def _leader(self, **kw):
        store = APIServer(clock=FakeClock())
        pers = Persistence(self.dir, fsync_every=1, **kw)
        pers.start(store)
        server = WALShipServer(pers)
        self.addCleanup(server.close)
        return store, pers, server

    def _follower(self, port, **kw):
        replica = FollowerReplica(RealClock(), name="sock-test")
        follower = ShipFollower("127.0.0.1", port, replica, **kw)
        self.addCleanup(follower.stop)
        return replica, follower

    def test_bootstrap_then_stream(self):
        store, pers, server = self._leader()
        store.create(_obj("pre-0"))  # durable before the follower exists
        pers.flush()
        replica, follower = self._follower(server.port)
        self.assertTrue(follower.wait_connected(5.0))
        for i in range(10):
            store.create(_obj(f"live-{i}"))
        pers.flush()
        self.assertTrue(_wait(lambda: len(replica.store) == 11))
        self.assertEqual(
            replica.state(),
            canonical_state(store.all_objects(), store._rv),
        )

    def test_reconnect_rebootstraps_no_miss_no_double_apply(self):
        store, pers, server = self._leader()
        replica, follower = self._follower(server.port)
        self.assertTrue(follower.wait_connected(5.0))
        for i in range(5):
            store.create(_obj(f"a-{i}"))
        pers.flush()
        self.assertTrue(_wait(lambda: len(replica.store) == 5))

        # Sever every server-side connection mid-subscription; keep
        # writing while the follower is dark.
        for conn in list(server._conns):
            conn.close()
        for i in range(5):
            store.create(_obj(f"b-{i}"))
        pers.flush()

        # The follower redials the same (still-listening) server and
        # re-bootstraps: the dark-window records arrive via the
        # bootstrap, the post-reconnect stream appends from there.
        self.assertTrue(_wait(lambda: follower.reconnects >= 1, timeout=10))
        for i in range(5):
            store.create(_obj(f"c-{i}"))
        pers.flush()
        self.assertTrue(_wait(lambda: len(replica.store) == 15, timeout=10))
        # No miss, no double apply: exact state AND exact rv.
        self.assertEqual(
            replica.state(),
            canonical_state(store.all_objects(), store._rv),
        )

    def test_reconnect_counts_into_metrics(self):
        from cron_operator_tpu.runtime.manager import Metrics
        metrics = Metrics()
        store, pers, server = self._leader()
        replica, follower = self._follower(server.port, metrics=metrics)
        self.assertTrue(follower.wait_connected(5.0))
        for conn in list(server._conns):
            conn.close()
        self.assertTrue(_wait(lambda: follower.reconnects >= 1, timeout=10))
        self.assertTrue(_wait(
            lambda: metrics.counters.get(
                "shard_follower_reconnects_total", 0) >= 1,
        ))

    def test_torn_wire_frame_equals_disk_replay(self):
        """Satellite: a WAL record torn on the WIRE (peer death mid-
        frame) is never applied partially — the follower's end state
        equals an independent replay of the on-disk WAL."""
        store, pers, server = self._leader()
        replica, follower = self._follower(server.port)
        self.assertTrue(follower.wait_connected(5.0))
        for i in range(8):
            store.create(_obj(f"w-{i}"))
        pers.flush()
        self.assertTrue(_wait(lambda: len(replica.store) == 8))

        # Tear the connection while a frame is mid-flight: grab the live
        # server-side socket and write a deliberately truncated frame
        # around the sink (the sink itself only ships whole flushes).
        conn = list(server._conns)[0]
        import struct
        torn = b'{"op":"put","rv":999,"obj":{"tor'  # mid-record
        conn.sock.sendall(
            struct.pack("!cI", FRAME_WAL, len(torn) + 40) + torn
        )
        conn.close()  # death mid-frame: EOF before the length is met

        # The follower discards the torn frame whole, reconnects, and
        # re-bootstraps; rv=999 must appear nowhere.
        self.assertTrue(_wait(lambda: follower.reconnects >= 1, timeout=10))
        self.assertTrue(_wait(
            lambda: follower.bootstraps >= 2, timeout=10))
        replay = Persistence(self.dir).recover()
        self.assertTrue(_wait(
            lambda: replica.state() == canonical_state(
                replay.objects, replay.rv),
            timeout=10,
        ))
        self.assertEqual(int(replica.store._rv), 8)

    def test_backoff_resets_only_after_successful_bootstrap(self):
        """Satellite: the reconnect ladder resets at the first PROVEN
        link (a delivered bootstrap), and only there — so a follower
        coming back from a long outage retries its healthy leader at
        base delay instead of dragging the outage's cap behind it."""
        from cron_operator_tpu.runtime.manager import Metrics
        from cron_operator_tpu.runtime.transport import RECONNECT_BASE_S
        metrics = Metrics()
        # Reserve a port, then leave it dead: every dial is refused.
        probe = socket.create_server(("127.0.0.1", 0))
        port = probe.getsockname()[1]
        probe.close()
        replica = FollowerReplica(RealClock(), name="backoff-test")
        follower = ShipFollower("127.0.0.1", port, replica, metrics=metrics)
        self.addCleanup(follower.stop)
        # Refusals climb the ladder well past base.
        self.assertTrue(_wait(
            lambda: follower.current_backoff_s >= RECONNECT_BASE_S * 8,
            timeout=10,
        ))
        gauge = f'shard_follower_reconnect_backoff_seconds{{port="{port}"}}'
        self.assertEqual(metrics.gauges.get(gauge),
                         follower.current_backoff_s)

        # The leader comes up on that port; the next dial bootstraps.
        store = APIServer(clock=FakeClock())
        pers = Persistence(self.dir, fsync_every=1)
        pers.start(store)
        server = WALShipServer(pers, port=port)
        self.addCleanup(server.close)
        self.assertTrue(follower.wait_connected(10.0))
        boots = follower.bootstraps

        # Drop the stream: because a bootstrap was delivered, the very
        # next delay is BASE again — not the refused-era ladder value.
        for conn in list(server._conns):
            conn.close()
        self.assertTrue(_wait(
            lambda: follower.bootstraps > boots, timeout=10))
        self.assertTrue(_wait(
            lambda: follower.current_backoff_s == RECONNECT_BASE_S,
            timeout=10,
        ))
        self.assertEqual(metrics.gauges.get(gauge), RECONNECT_BASE_S)

    def test_tcp_accept_alone_does_not_reset_backoff(self):
        """The gray case the reset rule exists for: a listener that
        accepts and hangs up before any bootstrap proves nothing, so
        the ladder keeps climbing."""
        listener = socket.create_server(("127.0.0.1", 0))
        listener.settimeout(0.2)
        port = listener.getsockname()[1]
        stop = threading.Event()

        def accept_and_slam():
            while not stop.is_set():
                try:
                    sock, _ = listener.accept()
                except socket.timeout:
                    continue
                except OSError:
                    return
                sock.close()

        t = threading.Thread(target=accept_and_slam, daemon=True)
        t.start()
        self.addCleanup(listener.close)
        self.addCleanup(stop.set)

        from cron_operator_tpu.runtime.transport import RECONNECT_BASE_S
        replica = FollowerReplica(RealClock(), name="slam-test")
        follower = ShipFollower("127.0.0.1", port, replica)
        self.addCleanup(follower.stop)
        self.assertTrue(_wait(
            lambda: follower.current_backoff_s >= RECONNECT_BASE_S * 8,
            timeout=10,
        ))
        self.assertEqual(follower.bootstraps, 0)

    def test_wedged_socket_stalls_leader_side_not_writers(self):
        """Satellite: a follower that stops reading must not block the
        leader's write path — the bounded ship queue drops whole and
        marks the connection for resync."""
        store, pers, server = self._leader()
        # Tiny queue so the wedge trips fast.
        server.max_buffered_bytes = 2048
        raw = socket.create_connection(("127.0.0.1", server.port))
        self.addCleanup(raw.close)
        # Read the bootstrap frame, then go silent (never read again)
        # with a zero receive window soon after.
        read_frame(raw)
        self.assertTrue(_wait(lambda: server.connections() == 1))
        sink = list(server._conns)[0].sink
        sink.max_buffered_bytes = 2048

        t0 = time.monotonic()
        for i in range(300):
            store.create(_obj(f"w-{i}", ns=f"ns-{i % 7}"))
        elapsed = time.monotonic() - t0
        pers.flush()
        # Writers never waited on the wedged socket.
        self.assertLess(elapsed, 5.0)
        self.assertEqual(len(store), 300)


class TestLeaseFile(_TmpDirTest):
    def _lease(self, holder="a", ttl=0.5):
        return LeaseFile(os.path.join(self.dir, "lease.json"),
                         holder=holder, ttl_s=ttl)

    def test_acquire_renew_expire(self):
        lease = self._lease()
        self.assertTrue(lease.expired())  # no file yet
        gen = lease.acquire()
        self.assertEqual(gen, 1)
        self.assertFalse(lease.expired())
        doc = lease.read()
        self.assertEqual(doc["holder"], "a")
        self.assertEqual(doc["pid"], os.getpid())
        time.sleep(0.7)
        self.assertTrue(lease.expired())

    def test_takeover_increments_generation(self):
        a = self._lease("a")
        a.acquire()
        b = self._lease("b")
        self.assertEqual(b.acquire(), 2)
        self.assertEqual(b.read()["holder"], "b")

    def test_heartbeat_keeps_lease_fresh(self):
        lease = self._lease(ttl=0.4)
        lease.acquire()
        lease.start_heartbeat()
        self.addCleanup(lease.stop_heartbeat)
        time.sleep(1.0)  # several TTLs
        self.assertFalse(lease.expired())
        lease.stop_heartbeat()
        time.sleep(0.6)
        self.assertTrue(lease.expired())

    def test_wait_fresh_arms_before_expiry_watch(self):
        # The standby rule: "no lease yet" is a booting leader, not a
        # dead one — wait_fresh must NOT pass until a live lease exists.
        lease = self._lease(ttl=0.4)
        self.assertFalse(
            lease.wait_fresh(poll_s=0.02, timeout=0.2))
        lease.acquire()
        self.assertTrue(lease.wait_fresh(poll_s=0.02, timeout=1.0))
        self.assertTrue(lease.wait_expired(poll_s=0.02, timeout=2.0))

    def test_atomic_rotation_never_shows_torn_lease(self):
        lease = self._lease(ttl=5.0)
        lease.acquire()
        stop = threading.Event()
        torn = []

        def reader():
            other = self._lease("reader")
            while not stop.is_set():
                if other.read() is None:
                    torn.append(1)

        t = threading.Thread(target=reader)
        t.start()
        for _ in range(200):
            lease.renew()
        stop.set()
        t.join(timeout=5)
        self.assertEqual(torn, [])


class TestLeaseFileClockJumps(_TmpDirTest):
    """Satellite: lease heartbeat/TTL math rides MONOTONIC time. An NTP
    step on the observing host can neither fake freshness (backwards
    jump) nor evict a live leader (forward jump). Tests stub the
    injectable clocks — no sleeping, no real NTP."""

    def _pair(self, ttl=10.0):
        path = os.path.join(self.dir, "lease.json")
        leader = LeaseFile(path, holder="leader", ttl_s=ttl)
        standby = LeaseFile(path, holder="standby", ttl_s=ttl)
        return leader, standby

    def test_forward_wall_jump_does_not_evict_live_leader(self):
        leader, standby = self._pair(ttl=10.0)
        wall, mono = [1000.0], [500.0]
        leader._time = standby._time = lambda: wall[0]
        standby._mono = lambda: mono[0]
        leader.acquire()
        self.assertFalse(standby.expired())
        # NTP slams the wall clock an hour forward; one real second
        # passes. Naive "now - renewed_at" math would read the live
        # lease as 3600s stale and promote a second leader.
        wall[0] += 3600.0
        mono[0] += 1.0
        self.assertFalse(standby.expired())
        # And it stays live across the leader's next renewal too.
        leader.renew()
        mono[0] += 1.0
        self.assertFalse(standby.expired())

    def test_backward_wall_jump_cannot_fake_freshness(self):
        leader, standby = self._pair(ttl=10.0)
        wall, mono = [1000.0], [500.0]
        leader._time = standby._time = lambda: wall[0]
        standby._mono = lambda: mono[0]
        leader.acquire()
        self.assertFalse(standby.expired())
        # The leader dies; the observer's wall clock then steps BACK,
        # putting renewed_at in the future. Wall math would keep the
        # corpse "fresh" forever (negative age); monotonic elapsed time
        # still runs and must expire it.
        wall[0] -= 3600.0
        mono[0] += 11.0  # one TTL + 1s of real time, doc unchanged
        self.assertTrue(standby.expired())

    def test_cold_boot_on_stale_lease_expires_immediately(self):
        leader, standby = self._pair(ttl=10.0)
        wall, mono = [1000.0], [500.0]
        leader._time = standby._time = lambda: wall[0]
        standby._mono = lambda: mono[0]
        leader.acquire()
        # Hours pass before the standby's FIRST look: the seed-from-
        # renewed_at rule must read it expired at once, not wait a
        # fresh TTL of monotonic time.
        wall[0] += 3600.0
        self.assertTrue(standby.expired())

    def test_frozen_wall_clock_renewals_still_observed(self):
        # The beat counter: with the leader's wall clock frozen, every
        # renewal still changes the doc bytes, so the observer keeps
        # re-anchoring and the lease never falsely expires.
        leader, standby = self._pair(ttl=10.0)
        wall, mono = [1000.0], [500.0]
        leader._time = standby._time = lambda: wall[0]
        standby._mono = lambda: mono[0]
        leader.acquire()
        for _ in range(5):
            mono[0] += 8.0  # under a TTL since the last observed change
            self.assertTrue(leader.renew())
            self.assertFalse(standby.expired())
        # Renewals stop: expiry now arrives in monotonic time.
        mono[0] += 11.0
        self.assertTrue(standby.expired())


class TestCircuitBreaker(unittest.TestCase):
    """Per-shard breaker state machine (gray failures: wedged-but-alive
    shards answer slowly or never — fail fast, probe, recover)."""

    def _tripped(self, **kw):
        kw.setdefault("window", 10)
        kw.setdefault("min_samples", 5)
        kw.setdefault("error_threshold", 0.5)
        kw.setdefault("cooldown_s", 60.0)
        br = CircuitBreaker(**kw)
        for _ in range(5):
            br.record(False, 0.5)
        return br

    def test_trips_open_on_error_rate(self):
        br = self._tripped()
        self.assertEqual(br.state, BREAKER_OPEN)
        self.assertEqual(br.trips, 1)
        self.assertFalse(br.allow())
        self.assertFalse(br.allow())
        self.assertEqual(br.fast_failures, 2)

    def test_min_samples_guard(self):
        br = CircuitBreaker(min_samples=5)
        for _ in range(4):  # 100% failure but too few samples
            br.record(False, 0.5)
        self.assertEqual(br.state, BREAKER_CLOSED)
        self.assertTrue(br.allow())

    def test_half_open_admits_exactly_one_probe_then_closes(self):
        br = self._tripped(cooldown_s=0.05)
        time.sleep(0.06)
        self.assertTrue(br.allow())    # the probe
        self.assertFalse(br.allow())   # everyone else still fails fast
        br.record(True, 0.01)          # probe healthy
        self.assertEqual(br.state, BREAKER_CLOSED)
        self.assertTrue(br.allow())
        # The wedged-era window is forgotten: one fresh failure must not
        # immediately re-trip.
        br.record(False, 0.5)
        self.assertEqual(br.state, BREAKER_CLOSED)

    def test_half_open_admits_exactly_one_probe_under_race(self):
        """Satellite: N threads hit allow() the instant the cooldown
        lapses — exactly ONE wins the probe slot. Two probes against a
        still-wedged shard is two timeouts' worth of user latency; zero
        probes means the breaker never recovers."""
        br = self._tripped(cooldown_s=0.05)
        for _ in range(3):  # several open → half-open cycles
            time.sleep(0.06)
            n = 16
            admitted = []
            barrier = threading.Barrier(n)

            def racer():
                barrier.wait()
                if br.allow():
                    admitted.append(1)

            threads = [threading.Thread(target=racer) for _ in range(n)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=5)
            self.assertEqual(len(admitted), 1)
            self.assertEqual(br.state, BREAKER_HALF_OPEN)
            br.record(False, 0.5)  # probe fails: re-open, race again
            self.assertEqual(br.state, BREAKER_OPEN)

    def test_half_open_probe_failure_reopens(self):
        br = self._tripped(cooldown_s=0.05)
        time.sleep(0.06)
        self.assertTrue(br.allow())
        br.record(False, 0.5)
        self.assertEqual(br.state, BREAKER_OPEN)
        self.assertFalse(br.allow())

    def test_slow_success_scores_as_failure(self):
        # Wedged-but-alive shards often answer *eventually*: latency
        # over the threshold is a failure even with a 2xx.
        br = CircuitBreaker(min_samples=5, latency_threshold_s=0.1)
        for _ in range(5):
            br.record(True, 0.5)
        self.assertEqual(br.state, BREAKER_OPEN)

    def test_stats_surface(self):
        br = self._tripped()
        s = br.stats()
        self.assertEqual(s["state"], "open")
        self.assertEqual(s["samples"], 5)
        self.assertEqual(s["error_rate"], 1.0)
        self.assertEqual(s["trips"], 1)


class TestFencing(_TmpDirTest):
    """Lease-generation fencing tokens: the in-process seams of I10."""

    def test_fenced_persistence_fails_closed_before_commit(self):
        store = APIServer(clock=FakeClock())
        pers = Persistence(self.dir, fsync_every=1)
        pers.start(store)
        self.addCleanup(pers.close)
        pers.set_generation(1)
        store.create(_obj("pre"))
        pers.flush()
        pers.fence(2)
        with self.assertRaises(FencedError):
            store.create(_obj("poison"))
        # Fail CLOSED: the append died before the in-memory commit, so
        # neither memory nor disk saw the dead epoch's write.
        self.assertEqual(len(store), 1)
        self.assertGreaterEqual(pers.fenced_appends, 1)
        replay = Persistence(self.dir).recover()
        self.assertEqual(
            [o["metadata"]["name"] for o in replay.objects], ["pre"])

    def test_generation_stamped_and_recovered(self):
        store = APIServer(clock=FakeClock())
        pers = Persistence(self.dir, fsync_every=1)
        pers.start(store)
        pers.set_generation(3)
        store.create(_obj("g"))
        pers.flush()
        pers.close()
        with open(os.path.join(self.dir, "wal.jsonl")) as f:
            recs = [json.loads(l) for l in f if l.strip()]
        self.assertTrue(all(r.get("gen") == 3 for r in recs))
        replay = Persistence(self.dir).recover()
        self.assertEqual(replay.generation, 3)

    def test_follower_rejects_stale_generation_records(self):
        replica = FollowerReplica(RealClock(), name="fence-test")
        fresh = _obj("fresh")
        stale = _obj("stale")
        replica.apply_bytes(
            json.dumps({"op": "put", "rv": 1, "gen": 2, "obj": fresh})
            .encode() + b"\n")
        self.assertEqual(replica.generation, 2)
        # A demoted leader's record over a still-open socket: refused.
        replica.apply_bytes(
            json.dumps({"op": "put", "rv": 2, "gen": 1, "obj": stale})
            .encode() + b"\n")
        self.assertEqual(replica.records_rejected, 1)
        self.assertEqual(
            [o["metadata"]["name"] for o in replica.store.all_objects()],
            ["fresh"])

    def test_lease_renew_self_demotes_on_foreign_generation(self):
        path = os.path.join(self.dir, "lease.json")
        a = LeaseFile(path, holder="a", ttl_s=5.0)
        a.acquire()
        lost = []
        a.on_lost = lost.append
        b = LeaseFile(path, holder="b", ttl_s=5.0)
        self.assertEqual(b.acquire(), 2)
        # a's renew READS before writing, observes the higher
        # generation, and demotes instead of clobbering b's tenure.
        self.assertFalse(a.renew())
        self.assertTrue(a.lost)
        self.assertEqual(len(lost), 1)
        self.assertEqual(a.read()["holder"], "b")
        # Renewals after demotion stay refusals; b's lease is untouched.
        self.assertFalse(a.renew())
        self.assertEqual(a.read()["generation"], 2)


class TestZombieLeaderFencing(_TmpDirTest):
    """The SIGSTOP/SIGCONT gray-failure regression: a leader frozen past
    its lease TTL wakes up as a zombie — alive, sockets bound, convinced
    it still owns the shard — and must fence itself before a single
    stale-epoch byte lands (invariant I10's process leg)."""

    def test_sigstop_zombie_fenced_on_wake(self):
        import signal
        import subprocess
        import sys
        import urllib.request

        api, ship, papi, pship = 26140, 26141, 26142, 26143
        logd = os.path.join(self.dir, "logs")
        os.makedirs(logd)

        def spawn(role_args, tag):
            log = open(os.path.join(logd, f"{tag}.log"), "ab")
            p = subprocess.Popen(
                [sys.executable, "-m", "cron_operator_tpu.cli.main",
                 "start", "--health-probe-bind-address", "0",
                 "--lease-ttl", "0.5"] + role_args,
                stdout=log, stderr=subprocess.STDOUT)
            return p

        def shard_doc(port):
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/debug/shards",
                        timeout=1.0) as r:
                    doc = json.loads(r.read())
                return (doc.get("shards") or [None])[0]
            except Exception:
                return None

        procs = []
        try:
            leader = spawn([
                "--shard-role", "shard", "--shard-index", "0",
                "--data-dir", self.dir,
                "--serve-api", f"127.0.0.1:{api}",
                "--ship-port", str(ship)], "leader")
            procs.append(leader)
            self.assertTrue(_wait(lambda: shard_doc(api), timeout=30))
            pid = shard_doc(api)["pid"]

            client = ShardClient(f"http://127.0.0.1:{api}")
            client.create(_obj("pre"))
            client.close()

            standby = spawn([
                "--shard-role", "standby", "--shard-index", "0",
                "--data-dir", self.dir,
                "--serve-api", f"127.0.0.1:{api}",
                "--ship-port", str(ship),
                "--promote-api-port", str(papi),
                "--promote-ship-port", str(pship)], "standby")
            procs.append(standby)
            time.sleep(0.5)  # follower bootstrap

            os.kill(pid, signal.SIGSTOP)
            self.assertTrue(_wait(lambda: shard_doc(papi), timeout=30))
            self.assertGreaterEqual(shard_doc(papi)["generation"], 2)

            os.kill(pid, signal.SIGCONT)
            self.assertTrue(_wait(
                lambda: (shard_doc(api) or {}).get("fenced"), timeout=10))

            # The zombie's front door is still up on the old port; its
            # fenced persistence must refuse the write BEFORE commit.
            zombie = ShardClient(f"http://127.0.0.1:{api}")
            with self.assertRaises(Exception):
                zombie.create(_obj("poison"))
            zombie.close()
            zdoc = shard_doc(api)
            self.assertGreaterEqual(zdoc["fenced_appends"], 1)
            self.assertTrue(zdoc["lease_lost"])

            # The promoted leader never saw the poison name.
            promoted = ShardClient(f"http://127.0.0.1:{papi}")
            self.assertIsNone(promoted.get_frozen(
                WORKLOAD_API_VERSION, WORKLOAD_KIND, "default", "poison"))
            promoted.close()
        finally:
            for p in procs:
                if p.poll() is None:
                    try:
                        p.send_signal(signal.SIGCONT)
                    except OSError:
                        pass
                    p.terminate()
            for p in procs:
                try:
                    p.wait(timeout=15)
                except subprocess.TimeoutExpired:
                    p.kill()


class TestShardClientSurface(unittest.TestCase):
    """ShardClient's embedded-store surface parity over a real front
    door (the router's view of one shard process)."""

    @classmethod
    def setUpClass(cls):
        from cron_operator_tpu.runtime.apiserver_http import HTTPAPIServer
        cls.store = APIServer(clock=FakeClock())
        cls.http = HTTPAPIServer(api=cls.store)
        cls.http.start()
        cls.client = ShardClient(f"http://127.0.0.1:{cls.http.port}")

    @classmethod
    def tearDownClass(cls):
        cls.client.close()
        cls.http.stop()
        cls.store.close()

    def test_crud_and_list_with_rv(self):
        self.client.create(_obj("s-0"))
        self.client.create(_obj("s-1"))
        items, rv = self.client.list_with_rv(
            WORKLOAD_API_VERSION, WORKLOAD_KIND)
        self.assertEqual(
            sorted(i["metadata"]["name"] for i in items), ["s-0", "s-1"])
        self.assertGreaterEqual(int(rv), 2)
        for i in items:  # apiVersion/kind restored on every item
            self.assertEqual(i["apiVersion"], WORKLOAD_API_VERSION)
            self.assertEqual(i["kind"], WORKLOAD_KIND)

    def test_get_frozen_is_existence_probe(self):
        self.client.create(_obj("s-frozen"))
        hit = self.client.get_frozen(
            WORKLOAD_API_VERSION, WORKLOAD_KIND, "default", "s-frozen")
        self.assertEqual(hit["metadata"]["name"], "s-frozen")
        self.assertIsNone(self.client.get_frozen(
            WORKLOAD_API_VERSION, WORKLOAD_KIND, "default", "nope"))

    def test_barrier_noops_and_truthiness(self):
        # The shard's own front door barriers writes on fsync before the
        # 2xx — by the time the client returns, durable means durable.
        self.assertTrue(self.client.wait_durable())
        self.assertTrue(self.client.flush())
        self.assertEqual(self.client.watch_backlog(), 0)
        self.assertTrue(bool(self.client))
        self.assertEqual(len(self.client), 0)


if __name__ == "__main__":
    unittest.main()
