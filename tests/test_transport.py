"""Multi-process control plane transport (runtime/transport.py).

Covers the socket/file analogs of the in-process seams:

- length-framed WAL shipping: a record split across TCP segments is
  reassembled whole, and a frame torn by the peer's death is discarded
  whole — the follower can never apply a partial record (invariant I6's
  socket leg);
- reconnect with re-bootstrap: a follower that loses its leader redials
  with bounded backoff and re-seeds from the leader's durable state, so
  no record is missed or double-applied across the drop;
- the on-disk lease: heartbeat renewal, expiry detection, generation
  increments, and the arm-only-after-fresh rule that keeps a standby
  from promoting into a leader that is still booting;
- the router's ShardClient surface parity (list_with_rv, get_frozen,
  barrier no-ops) over a real HTTP front door.
"""

import json
import os
import shutil
import socket
import tempfile
import threading
import time
import unittest

from cron_operator_tpu.runtime.kube import APIServer
from cron_operator_tpu.runtime.persistence import Persistence
from cron_operator_tpu.runtime.shard import FollowerReplica, canonical_state
from cron_operator_tpu.runtime.transport import (
    FRAME_BOOT,
    FRAME_WAL,
    LeaseFile,
    ShardClient,
    ShipFollower,
    WALShipServer,
    decode_bootstrap,
    encode_bootstrap,
    read_frame,
    write_frame,
)
from cron_operator_tpu.utils.clock import FakeClock, RealClock

WORKLOAD_API_VERSION = "kubeflow.org/v1"
WORKLOAD_KIND = "JAXJob"


def _obj(name: str, ns: str = "default") -> dict:
    return {
        "apiVersion": WORKLOAD_API_VERSION,
        "kind": WORKLOAD_KIND,
        "metadata": {"name": name, "namespace": ns},
        "spec": {"replicaSpecs": {"Worker": {"replicas": 1}}},
    }


def _wait(predicate, timeout=5.0, interval=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return predicate()


class _TmpDirTest(unittest.TestCase):
    def setUp(self):
        self.dir = tempfile.mkdtemp(prefix="transport-test-")
        self.addCleanup(shutil.rmtree, self.dir, ignore_errors=True)


class TestFraming(unittest.TestCase):
    def _pair(self):
        a, b = socket.socketpair()
        self.addCleanup(a.close)
        self.addCleanup(b.close)
        return a, b

    def test_round_trip_multiple_frames(self):
        a, b = self._pair()
        payloads = [b"", b"x", b'{"op":"put"}\n' * 100, os.urandom(4096)]
        for p in payloads:
            write_frame(a, FRAME_WAL, p)
        write_frame(a, FRAME_BOOT, b"boot")
        for p in payloads:
            self.assertEqual(read_frame(b), (FRAME_WAL, p))
        self.assertEqual(read_frame(b), (FRAME_BOOT, b"boot"))

    def test_eof_returns_none(self):
        a, b = self._pair()
        a.close()
        self.assertIsNone(read_frame(b))

    def test_torn_header_discarded_whole(self):
        a, b = self._pair()
        a.sendall(b"W\x00\x00")  # 3 of 5 header bytes, then death
        a.close()
        self.assertIsNone(read_frame(b))

    def test_torn_payload_discarded_whole(self):
        a, b = self._pair()
        import struct
        a.sendall(struct.pack("!cI", FRAME_WAL, 100) + b"only-part")
        a.close()
        # The reader must NOT hand back 9 bytes of a 100-byte record.
        self.assertIsNone(read_frame(b))

    def test_segmented_frame_reassembled(self):
        # One frame dribbled byte-by-byte (worst-case TCP segmentation)
        # still arrives as exactly one payload.
        a, b = self._pair()
        payload = b'{"op":"put","rv":7}\n'
        import struct
        wire = struct.pack("!cI", FRAME_WAL, len(payload)) + payload
        got = {}

        def reader():
            got["frame"] = read_frame(b)

        t = threading.Thread(target=reader)
        t.start()
        for i in range(len(wire)):
            a.sendall(wire[i:i + 1])
            time.sleep(0.0005)
        t.join(timeout=5)
        self.assertEqual(got["frame"], (FRAME_WAL, payload))

    def test_bootstrap_codec_round_trip(self):
        store = APIServer(clock=FakeClock())
        store.create(_obj("w-0"))
        store.create(_obj("w-1"))
        store.delete(WORKLOAD_API_VERSION, WORKLOAD_KIND, "default", "w-1")
        from cron_operator_tpu.runtime.persistence import RecoveredState
        state = RecoveredState(
            objects=store.all_objects(), rv=int(store._rv),
            wal_records_replayed=3,
        )
        state.wal_deleted_keys = [
            (WORKLOAD_API_VERSION, WORKLOAD_KIND, "default", "w-1")
        ]
        out = decode_bootstrap(encode_bootstrap(state))
        self.assertEqual(out.rv, state.rv)
        self.assertEqual(
            canonical_state(out.objects, out.rv),
            canonical_state(state.objects, state.rv),
        )
        self.assertEqual(out.wal_deleted_keys, state.wal_deleted_keys)


class TestShipSocket(_TmpDirTest):
    """Leader Persistence → WALShipServer → socket → ShipFollower."""

    def _leader(self, **kw):
        store = APIServer(clock=FakeClock())
        pers = Persistence(self.dir, fsync_every=1, **kw)
        pers.start(store)
        server = WALShipServer(pers)
        self.addCleanup(server.close)
        return store, pers, server

    def _follower(self, port, **kw):
        replica = FollowerReplica(RealClock(), name="sock-test")
        follower = ShipFollower("127.0.0.1", port, replica, **kw)
        self.addCleanup(follower.stop)
        return replica, follower

    def test_bootstrap_then_stream(self):
        store, pers, server = self._leader()
        store.create(_obj("pre-0"))  # durable before the follower exists
        pers.flush()
        replica, follower = self._follower(server.port)
        self.assertTrue(follower.wait_connected(5.0))
        for i in range(10):
            store.create(_obj(f"live-{i}"))
        pers.flush()
        self.assertTrue(_wait(lambda: len(replica.store) == 11))
        self.assertEqual(
            replica.state(),
            canonical_state(store.all_objects(), store._rv),
        )

    def test_reconnect_rebootstraps_no_miss_no_double_apply(self):
        store, pers, server = self._leader()
        replica, follower = self._follower(server.port)
        self.assertTrue(follower.wait_connected(5.0))
        for i in range(5):
            store.create(_obj(f"a-{i}"))
        pers.flush()
        self.assertTrue(_wait(lambda: len(replica.store) == 5))

        # Sever every server-side connection mid-subscription; keep
        # writing while the follower is dark.
        for conn in list(server._conns):
            conn.close()
        for i in range(5):
            store.create(_obj(f"b-{i}"))
        pers.flush()

        # The follower redials the same (still-listening) server and
        # re-bootstraps: the dark-window records arrive via the
        # bootstrap, the post-reconnect stream appends from there.
        self.assertTrue(_wait(lambda: follower.reconnects >= 1, timeout=10))
        for i in range(5):
            store.create(_obj(f"c-{i}"))
        pers.flush()
        self.assertTrue(_wait(lambda: len(replica.store) == 15, timeout=10))
        # No miss, no double apply: exact state AND exact rv.
        self.assertEqual(
            replica.state(),
            canonical_state(store.all_objects(), store._rv),
        )

    def test_reconnect_counts_into_metrics(self):
        from cron_operator_tpu.runtime.manager import Metrics
        metrics = Metrics()
        store, pers, server = self._leader()
        replica, follower = self._follower(server.port, metrics=metrics)
        self.assertTrue(follower.wait_connected(5.0))
        for conn in list(server._conns):
            conn.close()
        self.assertTrue(_wait(lambda: follower.reconnects >= 1, timeout=10))
        self.assertTrue(_wait(
            lambda: metrics.counters.get(
                "shard_follower_reconnects_total", 0) >= 1,
        ))

    def test_torn_wire_frame_equals_disk_replay(self):
        """Satellite: a WAL record torn on the WIRE (peer death mid-
        frame) is never applied partially — the follower's end state
        equals an independent replay of the on-disk WAL."""
        store, pers, server = self._leader()
        replica, follower = self._follower(server.port)
        self.assertTrue(follower.wait_connected(5.0))
        for i in range(8):
            store.create(_obj(f"w-{i}"))
        pers.flush()
        self.assertTrue(_wait(lambda: len(replica.store) == 8))

        # Tear the connection while a frame is mid-flight: grab the live
        # server-side socket and write a deliberately truncated frame
        # around the sink (the sink itself only ships whole flushes).
        conn = list(server._conns)[0]
        import struct
        torn = b'{"op":"put","rv":999,"obj":{"tor'  # mid-record
        conn.sock.sendall(
            struct.pack("!cI", FRAME_WAL, len(torn) + 40) + torn
        )
        conn.close()  # death mid-frame: EOF before the length is met

        # The follower discards the torn frame whole, reconnects, and
        # re-bootstraps; rv=999 must appear nowhere.
        self.assertTrue(_wait(lambda: follower.reconnects >= 1, timeout=10))
        self.assertTrue(_wait(
            lambda: follower.bootstraps >= 2, timeout=10))
        replay = Persistence(self.dir).recover()
        self.assertTrue(_wait(
            lambda: replica.state() == canonical_state(
                replay.objects, replay.rv),
            timeout=10,
        ))
        self.assertEqual(int(replica.store._rv), 8)

    def test_wedged_socket_stalls_leader_side_not_writers(self):
        """Satellite: a follower that stops reading must not block the
        leader's write path — the bounded ship queue drops whole and
        marks the connection for resync."""
        store, pers, server = self._leader()
        # Tiny queue so the wedge trips fast.
        server.max_buffered_bytes = 2048
        raw = socket.create_connection(("127.0.0.1", server.port))
        self.addCleanup(raw.close)
        # Read the bootstrap frame, then go silent (never read again)
        # with a zero receive window soon after.
        read_frame(raw)
        self.assertTrue(_wait(lambda: server.connections() == 1))
        sink = list(server._conns)[0].sink
        sink.max_buffered_bytes = 2048

        t0 = time.monotonic()
        for i in range(300):
            store.create(_obj(f"w-{i}", ns=f"ns-{i % 7}"))
        elapsed = time.monotonic() - t0
        pers.flush()
        # Writers never waited on the wedged socket.
        self.assertLess(elapsed, 5.0)
        self.assertEqual(len(store), 300)


class TestLeaseFile(_TmpDirTest):
    def _lease(self, holder="a", ttl=0.5):
        return LeaseFile(os.path.join(self.dir, "lease.json"),
                         holder=holder, ttl_s=ttl)

    def test_acquire_renew_expire(self):
        lease = self._lease()
        self.assertTrue(lease.expired())  # no file yet
        gen = lease.acquire()
        self.assertEqual(gen, 1)
        self.assertFalse(lease.expired())
        doc = lease.read()
        self.assertEqual(doc["holder"], "a")
        self.assertEqual(doc["pid"], os.getpid())
        time.sleep(0.7)
        self.assertTrue(lease.expired())

    def test_takeover_increments_generation(self):
        a = self._lease("a")
        a.acquire()
        b = self._lease("b")
        self.assertEqual(b.acquire(), 2)
        self.assertEqual(b.read()["holder"], "b")

    def test_heartbeat_keeps_lease_fresh(self):
        lease = self._lease(ttl=0.4)
        lease.acquire()
        lease.start_heartbeat()
        self.addCleanup(lease.stop_heartbeat)
        time.sleep(1.0)  # several TTLs
        self.assertFalse(lease.expired())
        lease.stop_heartbeat()
        time.sleep(0.6)
        self.assertTrue(lease.expired())

    def test_wait_fresh_arms_before_expiry_watch(self):
        # The standby rule: "no lease yet" is a booting leader, not a
        # dead one — wait_fresh must NOT pass until a live lease exists.
        lease = self._lease(ttl=0.4)
        self.assertFalse(
            lease.wait_fresh(poll_s=0.02, timeout=0.2))
        lease.acquire()
        self.assertTrue(lease.wait_fresh(poll_s=0.02, timeout=1.0))
        self.assertTrue(lease.wait_expired(poll_s=0.02, timeout=2.0))

    def test_atomic_rotation_never_shows_torn_lease(self):
        lease = self._lease(ttl=5.0)
        lease.acquire()
        stop = threading.Event()
        torn = []

        def reader():
            other = self._lease("reader")
            while not stop.is_set():
                if other.read() is None:
                    torn.append(1)

        t = threading.Thread(target=reader)
        t.start()
        for _ in range(200):
            lease.renew()
        stop.set()
        t.join(timeout=5)
        self.assertEqual(torn, [])


class TestShardClientSurface(unittest.TestCase):
    """ShardClient's embedded-store surface parity over a real front
    door (the router's view of one shard process)."""

    @classmethod
    def setUpClass(cls):
        from cron_operator_tpu.runtime.apiserver_http import HTTPAPIServer
        cls.store = APIServer(clock=FakeClock())
        cls.http = HTTPAPIServer(api=cls.store)
        cls.http.start()
        cls.client = ShardClient(f"http://127.0.0.1:{cls.http.port}")

    @classmethod
    def tearDownClass(cls):
        cls.client.close()
        cls.http.stop()
        cls.store.close()

    def test_crud_and_list_with_rv(self):
        self.client.create(_obj("s-0"))
        self.client.create(_obj("s-1"))
        items, rv = self.client.list_with_rv(
            WORKLOAD_API_VERSION, WORKLOAD_KIND)
        self.assertEqual(
            sorted(i["metadata"]["name"] for i in items), ["s-0", "s-1"])
        self.assertGreaterEqual(int(rv), 2)
        for i in items:  # apiVersion/kind restored on every item
            self.assertEqual(i["apiVersion"], WORKLOAD_API_VERSION)
            self.assertEqual(i["kind"], WORKLOAD_KIND)

    def test_get_frozen_is_existence_probe(self):
        self.client.create(_obj("s-frozen"))
        hit = self.client.get_frozen(
            WORKLOAD_API_VERSION, WORKLOAD_KIND, "default", "s-frozen")
        self.assertEqual(hit["metadata"]["name"], "s-frozen")
        self.assertIsNone(self.client.get_frozen(
            WORKLOAD_API_VERSION, WORKLOAD_KIND, "default", "nope"))

    def test_barrier_noops_and_truthiness(self):
        # The shard's own front door barriers writes on fsync before the
        # 2xx — by the time the client returns, durable means durable.
        self.assertTrue(self.client.wait_durable())
        self.assertTrue(self.client.flush())
        self.assertEqual(self.client.watch_backlog(), 0)
        self.assertTrue(bool(self.client))
        self.assertEqual(len(self.client), 0)


if __name__ == "__main__":
    unittest.main()
