"""Workqueue specs: per-item exponential backoff (client-go
ItemExponentialFailureRateLimiter parity), rate-limited/delayed add
ordering, shutdown wake/teardown semantics, and the stats() idleness
probe the chaos soak quiesces on."""

import threading
import time

from cron_operator_tpu.runtime.workqueue import (
    ItemExponentialBackoff,
    WorkQueue,
)


class TestItemExponentialBackoff:
    def test_delay_doubles_per_failure(self):
        rl = ItemExponentialBackoff(base_s=0.005, cap_s=1000.0)
        assert [rl.when("a") for _ in range(4)] == [
            0.005, 0.01, 0.02, 0.04,
        ]

    def test_items_backoff_independently(self):
        rl = ItemExponentialBackoff(base_s=0.005)
        rl.when("a")
        rl.when("a")
        assert rl.when("b") == 0.005  # fresh item starts at base

    def test_cap_is_1000s(self):
        rl = ItemExponentialBackoff(base_s=0.005, cap_s=1000.0)
        for _ in range(17):  # 0.005 * 2**17 = 655.36 — still under
            rl.when("a")
        assert rl.when("a") == 0.005 * 2 ** 17
        assert rl.when("a") == 1000.0  # 2**18 would be 1310.72 — capped

    def test_overflow_clamp_for_persistent_failures(self):
        # 2**n overflows float around n=1024; the limiter clamps the
        # exponent rather than raising OverflowError at failure ~1030.
        rl = ItemExponentialBackoff(base_s=0.005, cap_s=1000.0)
        for _ in range(2000):
            delay = rl.when("a")
            assert delay <= 1000.0
        assert rl.num_requeues("a") == 2000

    def test_forget_resets_backoff(self):
        rl = ItemExponentialBackoff(base_s=0.005)
        for _ in range(5):
            rl.when("a")
        assert rl.num_requeues("a") == 5
        rl.forget("a")
        assert rl.num_requeues("a") == 0
        assert rl.when("a") == 0.005

    def test_forget_unknown_item_is_noop(self):
        rl = ItemExponentialBackoff()
        rl.forget("ghost")
        assert rl.num_requeues("ghost") == 0

    def test_num_requeues_counts_without_mutating(self):
        rl = ItemExponentialBackoff()
        rl.when("a")
        assert rl.num_requeues("a") == 1
        assert rl.num_requeues("a") == 1  # reading doesn't bump


class TestRateLimitedAdds:
    def test_add_rate_limited_first_failure_is_near_immediate(self):
        q = WorkQueue()
        try:
            q.add_rate_limited("a")
            assert q.get(timeout=2.0) == "a"  # base 5ms delay
        finally:
            q.shut_down()

    def test_add_rate_limited_orders_by_accumulated_backoff(self):
        # "hot" has failed 6 times (320ms delay), "cold" once (5ms):
        # enqueued together, cold must surface first.
        q = WorkQueue()
        try:
            for _ in range(6):
                q.rate_limiter.when("hot")
            q.add_rate_limited("hot")
            q.add_rate_limited("cold")
            assert q.get(timeout=2.0) == "cold"
            q.done("cold")
            assert q.get(timeout=2.0) == "hot"
        finally:
            q.shut_down()

    def test_forget_propagates_to_rate_limiter(self):
        q = WorkQueue()
        try:
            for _ in range(8):
                q.rate_limiter.when("a")
            q.forget("a")
            assert q.rate_limiter.num_requeues("a") == 0
        finally:
            q.shut_down()

    def test_add_after_orders_by_deadline_not_insertion(self):
        q = WorkQueue()
        try:
            q.add_after("late", 0.25)
            q.add_after("early", 0.01)
            assert q.get(timeout=2.0) == "early"
            q.done("early")
            assert q.get(timeout=2.0) == "late"
        finally:
            q.shut_down()

    def test_add_after_zero_delay_enqueues_directly(self):
        q = WorkQueue()
        try:
            q.add_after("now", 0.0)
            assert q.stats()[0] == 1  # queued, no delayed entry
            assert q.get(timeout=1.0) == "now"
        finally:
            q.shut_down()


class TestStats:
    def test_stats_tracks_queued_processing_and_delayed(self):
        q = WorkQueue()
        try:
            assert q.stats() == (0, 0, None)
            q.add("a")
            assert q.stats() == (1, 0, None)
            assert q.get(timeout=1.0) == "a"
            assert q.stats() == (0, 1, None)  # being processed
            q.done("a")
            assert q.stats() == (0, 0, None)

            q.add_after("b", 30.0)
            queued, processing, next_delay = q.stats()
            assert (queued, processing) == (0, 0)
            assert next_delay is not None and 0 < next_delay <= 30.0
        finally:
            q.shut_down()

    def test_stats_delay_shrinks_toward_deadline(self):
        q = WorkQueue()
        try:
            q.add_after("b", 5.0)
            first = q.stats()[2]
            time.sleep(0.05)
            assert q.stats()[2] < first
        finally:
            q.shut_down()


class TestShutdown:
    def test_shut_down_wakes_untimed_getters(self):
        # Workers park in get(timeout=None) for process lifetime; a
        # shard teardown must release ALL of them promptly — a missed
        # notify here deadlocks Manager.stop() joining its workers.
        q = WorkQueue()
        results = []
        threads = [
            threading.Thread(target=lambda: results.append(q.get()))
            for _ in range(4)
        ]
        for t in threads:
            t.start()
        time.sleep(0.05)  # let every worker reach the untimed wait
        start = time.monotonic()
        q.shut_down()
        for t in threads:
            t.join(timeout=2.0)
        assert not any(t.is_alive() for t in threads)
        assert time.monotonic() - start < 1.0
        assert results == [None, None, None, None]

    def test_shut_down_joins_delay_thread(self):
        q = WorkQueue()
        q.add_after("pending", 60.0)
        q.shut_down()
        assert not q._delay_thread.is_alive()
        # dropped delayed adds leave a clean idle probe
        assert q.stats() == (0, 0, None)

    def test_done_after_shutdown_does_not_requeue_dirty_item(self):
        q = WorkQueue()
        q.add("a")
        assert q.get(timeout=1.0) == "a"
        q.add("a")  # dirty while processing → would re-queue on done()
        q.shut_down()
        q.done("a")
        assert q.stats() == (0, 0, None)
        assert q.get(timeout=0.1) is None

    def test_adds_after_shutdown_are_dropped(self):
        q = WorkQueue()
        q.shut_down()
        q.add("a")
        q.add_after("b", 0.0)
        q.add_rate_limited("c")
        assert q.stats() == (0, 0, None)
        assert q.get(timeout=0.1) is None
