"""Training-harness tests: sharded Trainer across parallelism modes, and the
executor→entrypoint integration (the local analog of the reference's
envtest-with-hand-set-status strategy, except here the training REALLY runs
— closing the e2e gap the reference left, SURVEY.md §4 item 2)."""

import jax
import jax.numpy as jnp
import pytest

from cron_operator_tpu.backends.local import LocalExecutor
from cron_operator_tpu.models import MLP, Bert, BertConfig
from cron_operator_tpu.parallel.mesh import mesh_for_devices
from cron_operator_tpu.runtime.kube import APIServer
from cron_operator_tpu.utils.clock import RealClock
from cron_operator_tpu.workloads import data as datasets
from cron_operator_tpu.workloads.train import TrainConfig, Trainer


@pytest.fixture(scope="module")
def cpus():
    return jax.devices("cpu")


def _mlp_trainer(mesh, cpus):
    with jax.default_device(cpus[0]):
        m = MLP(features=(64,))
        params = m.init(jax.random.PRNGKey(0), jnp.zeros((1, 28, 28, 1)))[
            "params"
        ]
        return Trainer(
            lambda p, x: m.apply({"params": p}, x), params, mesh,
            TrainConfig(optimizer="sgd", learning_rate=0.05),
        )


class TestTrainer:
    def test_dp_loss_decreases(self, cpus):
        mesh = mesh_for_devices(cpus)
        tr = _mlp_trainer(mesh, cpus)
        it = datasets.mnist_batches(64, seed=3)
        batch = next(it)  # overfit one batch: loss must drop
        losses = [tr.step(batch).loss for _ in range(10)]
        assert losses[-1] < losses[0]

    def test_state_is_sharded_fsdp(self, cpus):
        mesh = mesh_for_devices(cpus, fsdp=2)
        tr = _mlp_trainer(mesh, cpus)
        # The first Dense kernel is (784, 64): 784 % 2 == 0 → fsdp-sharded.
        leaf = tr.state.params["Dense_0"]["kernel"]
        assert "fsdp" in str(leaf.sharding.spec)

    def test_bert_tp_sp_step(self, cpus):
        mesh = mesh_for_devices(cpus, seq=2, tensor=2)
        with jax.default_device(cpus[0]):
            cfg = BertConfig.tiny(max_len=64, attention_impl="ring")
            m = Bert(cfg, mesh=mesh)
            params = m.init(
                jax.random.PRNGKey(0), jnp.zeros((1, 64), jnp.int32)
            )["params"]
            tr = Trainer(
                lambda p, x: m.apply({"params": p}, x), params, mesh,
                TrainConfig(seq_dim_in_batch=1, labels_follow_seq=True),
            )
            it = datasets.token_batches(4, 64, cfg.vocab_size)
            s1, s2 = tr.step(next(it)), tr.step(next(it))
        assert jnp.isfinite(s1.loss) and jnp.isfinite(s2.loss)

    def test_gpt_ring_sp_step_with_moe(self, cpus):
        """GPT under seq-parallel ring attention with MoE blocks: the full
        long-context + expert composition trains one sharded step."""
        from cron_operator_tpu.models import GPT, GPTConfig

        mesh = mesh_for_devices(cpus, seq=2)
        with jax.default_device(cpus[0]):
            cfg = GPTConfig.tiny(
                max_len=64, attention_impl="ring",
                moe_every=2, num_experts=4,
            )
            m = GPT(cfg, mesh=mesh)
            params = m.init(
                jax.random.PRNGKey(0), jnp.zeros((1, 64), jnp.int32)
            )["params"]
            tr = Trainer(
                lambda p, x: m.apply({"params": p}, x), params, mesh,
                TrainConfig(seq_dim_in_batch=1, labels_follow_seq=True,
                            aux_loss_in_output=True),
            )
            it = datasets.token_batches(4, 64, cfg.vocab_size)
            s1, s2 = tr.step(next(it)), tr.step(next(it))
        assert jnp.isfinite(s1.loss) and jnp.isfinite(s2.loss)

    def test_gpt_gqa_rope_under_ring_sp(self, cpus):
        """GQA + RoPE must compose with ring sequence parallelism: RoPE
        rotates at global positions before the seq shard_map, and the
        broadcast K/V heads ride the ring like MHA ones."""
        from cron_operator_tpu.models import GPT, GPTConfig

        mesh = mesh_for_devices(cpus, seq=2)
        with jax.default_device(cpus[0]):
            cfg = GPTConfig.tiny(
                max_len=64, attention_impl="ring",
                num_kv_heads=2, rope=True,
            )
            m = GPT(cfg, mesh=mesh)
            params = m.init(
                jax.random.PRNGKey(0), jnp.zeros((1, 64), jnp.int32)
            )["params"]
            tr = Trainer(
                lambda p, x: m.apply({"params": p}, x), params, mesh,
                TrainConfig(seq_dim_in_batch=1, labels_follow_seq=True,
                            aux_loss_in_output=True),
            )
            it = datasets.token_batches(8, 64, cfg.vocab_size)
            s1, s2 = tr.step(next(it)), tr.step(next(it))
        assert jnp.isfinite(s1.loss) and jnp.isfinite(s2.loss)

    def test_profile_trace_written(self, tmp_path):
        """param.profile_dir captures a jax.profiler trace of the
        steady-state steps (SURVEY.md §5: the reference has no
        tracing/profiling at all)."""
        from cron_operator_tpu.backends.registry import (
            JobContext,
            resolve_entrypoint,
        )

        ctx = JobContext(
            name="prof", namespace="default", job={},
            params={
                "steps": "2", "batch_size": "8", "platform": "cpu",
                "profile_dir": str(tmp_path / "trace"),
            },
        )
        resolve_entrypoint("mnist")(ctx)
        assert ctx.progress["profile_dir"] == str(tmp_path / "trace")
        produced = list((tmp_path / "trace").rglob("*"))
        assert any(p.is_file() for p in produced), (
            "profiler wrote no trace files"
        )

    def test_gpt_entrypoint_registered(self):
        from cron_operator_tpu.backends.registry import resolve_entrypoint

        assert resolve_entrypoint("gpt").__name__ == "gpt"

    def test_remat_matches_no_remat(self, cpus):
        """jax.checkpoint must not change the math."""
        mesh = mesh_for_devices(cpus)
        with jax.default_device(cpus[0]):
            m = MLP(features=(32,))

            def init():
                # Separate trees per trainer: Trainer donates its state, so
                # sharing one params tree across two trainers would leave
                # the second holding deleted buffers.
                return m.init(
                    jax.random.PRNGKey(0), jnp.zeros((1, 28, 28, 1))
                )["params"]

            apply = lambda p, x: m.apply({"params": p}, x)  # noqa: E731
            t1 = Trainer(apply, init(), mesh,
                         TrainConfig(optimizer="sgd", remat=False))
            t2 = Trainer(apply, init(), mesh,
                         TrainConfig(optimizer="sgd", remat=True))
            batch = next(datasets.mnist_batches(32, seed=5))
            l1, l2 = t1.step(batch).loss, t2.step(batch).loss
        assert abs(l1 - l2) < 1e-5


class TestRunner:
    """The container-side entrypoint (workloads/runner.py)."""

    def test_single_process_run(self, capsys):
        from cron_operator_tpu.workloads import runner

        rc = runner.main(
            ["mnist", "steps=1", "batch_size=8", "platform=cpu"]
        )
        assert rc == 0
        out = capsys.readouterr().out
        assert '"steps_done": 1' in out

    def test_usage_error(self):
        from cron_operator_tpu.workloads import runner

        assert runner.main([]) == 2


class TestExecutorRunsTraining:
    """Full loop: JAXJob object → executor → real JAX training → status."""

    def _jaxjob(self, name, params):
        ann = {"tpu.kubedl.io/entrypoint": "mnist"}
        ann.update({
            f"tpu.kubedl.io/param.{k}": str(v) for k, v in params.items()
        })
        return {
            "apiVersion": "kubeflow.org/v1",
            "kind": "JAXJob",
            "metadata": {
                "name": name, "namespace": "default", "annotations": ann,
            },
            "spec": {"replicaSpecs": {"Worker": {"replicas": 1}}},
        }

    def test_mnist_job_trains_and_succeeds(self):
        api = APIServer(clock=RealClock())
        ex = LocalExecutor(api)
        ex.start()
        try:
            api.create(self._jaxjob(
                "mnist-e2e",
                {"steps": 2, "batch_size": 16, "platform": "cpu"},
            ))
            assert ex.wait_idle(timeout=120.0)
        finally:
            ex.stop()
        job = api.get("kubeflow.org/v1", "JAXJob", "default", "mnist-e2e")
        conds = [c["type"] for c in job["status"]["conditions"]]
        assert conds[-1] == "Succeeded"
        prog = job["status"]["trainingProgress"]
        assert prog["steps_done"] == 2
        assert prog["first_step_at"] >= prog["started_at"]
        assert jnp.isfinite(prog["last_loss"])


class TestMeshResolution:
    def test_slices_param_builds_hybrid_mesh(self):
        """param.slices=2 routes to the multi-slice hybrid mesh: data
        outermost (DCN), model axes within a slice."""
        from cron_operator_tpu.backends.registry import JobContext
        from cron_operator_tpu.workloads.entrypoints import _mesh

        ctx = JobContext(
            name="m", namespace="default", job={},
            params={"slices": "2", "tensor": "2", "platform": "cpu"},
        )
        mesh = _mesh(ctx)
        assert mesh.axis_names[0] == "data"
        assert dict(zip(mesh.axis_names, mesh.devices.shape)) == {
            "data": 4, "tensor": 2,
        }


class TestPrefetcher:
    def test_yields_same_batches_in_order(self):
        from cron_operator_tpu.workloads.data import Prefetcher

        src = [{"x": i} for i in range(20)]
        pf = Prefetcher(iter(src), place=lambda b: b, depth=3)
        got = list(pf)
        pf.close()
        assert got == src

    def test_close_unblocks_infinite_producer(self):
        from cron_operator_tpu.workloads.data import Prefetcher

        def forever():
            i = 0
            while True:
                yield {"x": i}
                i += 1

        pf = Prefetcher(forever(), place=lambda b: b, depth=2)
        assert next(pf)["x"] == 0
        pf.close()
        assert not pf._thread.is_alive(), "producer must stop on close"

    def test_iterator_exception_propagates(self):
        from cron_operator_tpu.workloads.data import Prefetcher

        def bad():
            yield {"x": 0}
            raise RuntimeError("data source broke")

        pf = Prefetcher(bad(), place=lambda b: b, depth=2)
        assert next(pf)["x"] == 0
        import pytest as _pytest

        with _pytest.raises(RuntimeError, match="data source broke"):
            while True:
                next(pf)
        pf.close()

    def test_next_after_exhaustion_keeps_raising(self):
        """Iterator protocol: next() after StopIteration (or close) must
        raise again, never block on the empty queue."""
        from cron_operator_tpu.workloads.data import Prefetcher

        pf = Prefetcher(iter([{"x": 1}]), place=lambda b: b, depth=2)
        assert list(pf) == [{"x": 1}]
        import pytest as _pytest

        with _pytest.raises(StopIteration):
            next(pf)
        pf.close()
        with _pytest.raises(StopIteration):
            next(pf)

    def test_trainer_prefetch_matches_sync_losses(self, cpus):
        """prefetch must change timing only — the loss sequence on
        deterministic data is identical to the synchronous path."""
        from cron_operator_tpu.models import MLP

        def run(prefetch):
            mesh = mesh_for_devices(cpus)
            m = MLP(features=(32,))
            params = m.init(
                jax.random.PRNGKey(0), jnp.zeros((1, 28, 28, 1))
            )["params"]
            tr = Trainer(
                lambda p, x: m.apply({"params": p}, x), params, mesh,
                TrainConfig(optimizer="sgd", prefetch=prefetch),
            )
            stats = tr.run(datasets.mnist_batches(16, seed=13), steps=3)
            return [s.loss for s in stats]

        assert run(0) == run(2)


class TestDeviceBatches:
    """On-device synthetic generation (workloads.data.device_*): the
    TPU-first default for param.data — per-step host traffic is one folded
    PRNG key, not the batch (decisive on remote/tunneled devices)."""

    @pytest.mark.parametrize(
        "host_fn,dev_fn,args",
        [
            ("mnist_batches", "device_mnist_batches", (4,)),
            ("imagenet_batches", "device_imagenet_batches", (2, 32)),
            ("token_batches", "device_token_batches", (2, 16, 100)),
            (
                "causal_token_batches",
                "device_causal_token_batches",
                (2, 16, 100),
            ),
        ],
    )
    def test_shapes_and_dtypes_match_host_variant(
        self, cpus, host_fn, dev_fn, args
    ):
        with jax.default_device(cpus[0]):
            host = next(getattr(datasets, host_fn)(*args))
            dev = next(getattr(datasets, dev_fn)(*args))
        assert set(dev) == set(host)
        for key in host:
            assert dev[key].shape == host[key].shape, key
            assert dev[key].dtype == host[key].dtype, key

    def test_deterministic_per_seed_and_step(self, cpus):
        with jax.default_device(cpus[0]):
            a = datasets.device_token_batches(2, 16, 100, seed=7)
            b = datasets.device_token_batches(2, 16, 100, seed=7)
            for _ in range(3):  # same seed → identical stream
                ba, bb = next(a), next(b)
                assert (ba["x"] == bb["x"]).all()
            # different seed → different stream at the SAME step index
            # (anything else would also pass if seed were ignored).
            first_of_7 = next(
                datasets.device_token_batches(2, 16, 100, seed=7)
            )
            first_of_8 = next(
                datasets.device_token_batches(2, 16, 100, seed=8)
            )
            assert not (first_of_7["x"] == first_of_8["x"]).all()

    def test_batches_vary_per_step(self, cpus):
        with jax.default_device(cpus[0]):
            it = datasets.device_imagenet_batches(2, 32)
            assert not (next(it)["x"] == next(it)["x"]).all()

    def test_sharded_placement(self, cpus):
        """shardings= places the generated batch straight onto the mesh
        (Trainer.batch_sharding), no host round trip."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = mesh_for_devices(cpus)
        sh = {
            "x": NamedSharding(mesh, P(("data",))),
            "y": NamedSharding(mesh, P(("data",))),
        }
        batch = next(
            datasets.device_token_batches(8, 16, 100, shardings=sh)
        )
        assert batch["x"].sharding == sh["x"]


class TestSyncEvery:
    """TrainConfig.sync_every: the blocking loss fetch is a full host↔
    device round trip (~80 ms over a tunnel), so steady-state throughput
    amortizes it; the first and last steps always sync."""

    def _run(self, cpus, sync_every, steps, stop_after=None):
        mesh = mesh_for_devices(cpus)
        tr = _mlp_trainer(mesh, cpus)
        tr.config.sync_every = sync_every
        stop = (
            None if stop_after is None
            else (lambda: tr.steps_done >= stop_after)
        )
        stats = tr.run(
            datasets.mnist_batches(8, seed=3), steps=steps,
            should_stop=stop,
        )
        return tr, stats

    def _stats(self, cpus, sync_every, steps, stop_after=None):
        return self._run(cpus, sync_every, steps, stop_after)[1]

    def test_sync_cadence(self, cpus):
        stats = self._stats(cpus, sync_every=3, steps=5)
        synced = [s.loss is not None for s in stats]
        # first (north-star anchor), every 3rd, and last.
        assert synced == [True, False, True, False, True]

    def test_every_step_syncs_by_default(self, cpus):
        stats = self._stats(cpus, sync_every=1, steps=3)
        assert all(s.loss is not None for s in stats)

    def test_early_stop_drains_device(self, cpus, monkeypatch):
        """A should_stop exit mid-window must not leave device programs in
        flight: run()'s finally must block on the state (the drain is also
        charged to the last recorded step's time)."""
        from cron_operator_tpu.workloads import train as train_mod

        drained = []
        orig = jax.block_until_ready
        monkeypatch.setattr(
            train_mod.jax, "block_until_ready",
            lambda t: drained.append(True) or orig(t),
        )
        tr, stats = self._run(cpus, sync_every=10, steps=50, stop_after=4)
        assert len(stats) == 4
        assert stats[-1].loss is None  # stopped between syncs
        assert drained, "finally-drain must block on the state"


class TestLRSchedules:
    def test_warmup_cosine_shape(self):
        cfg = TrainConfig(
            learning_rate=0.1, lr_schedule="warmup_cosine",
            warmup_steps=10, schedule_steps=100,
        )
        lr = cfg.lr_at()
        assert lr(0) == 0.0                     # warmup starts at zero
        assert abs(lr(10) - 0.1) < 1e-6        # peak at warmup end
        assert lr(50) < 0.1                     # decaying
        assert lr(100) < lr(50)                 # monotone decay
        # make_optimizer accepts the schedule (optax injects it)
        cfg.make_optimizer()

    def test_cosine_decays_to_zero(self):
        cfg = TrainConfig(learning_rate=0.2, lr_schedule="cosine",
                          schedule_steps=40)
        lr = cfg.lr_at()
        assert abs(lr(0) - 0.2) < 1e-6
        assert lr(40) < 1e-6

    def test_constant_and_unknown(self):
        assert TrainConfig(learning_rate=0.3).lr_at()(999) == 0.3
        import pytest as _pytest

        with _pytest.raises(ValueError, match="unknown lr_schedule"):
            TrainConfig(lr_schedule="nope").lr_at()

    def test_schedule_trains(self, cpus):
        """A scheduled optimizer steps the sharded trainer end to end
        (the schedule's step count lives in TrainState, so checkpoint
        resume lands on the right point of the curve for free)."""
        mesh = mesh_for_devices(cpus)
        with jax.default_device(cpus[0]):
            m = MLP(features=(32,))
            params = m.init(
                jax.random.PRNGKey(0), jnp.zeros((1, 28, 28, 1))
            )["params"]
            tr = Trainer(
                lambda p, x: m.apply({"params": p}, x), params, mesh,
                TrainConfig(
                    optimizer="sgd", learning_rate=0.05,
                    lr_schedule="warmup_cosine", warmup_steps=2,
                    schedule_steps=6,
                ),
            )
            stats = tr.run(datasets.mnist_batches(8, seed=3), steps=4)
        assert len(stats) == 4
        assert all(
            s.loss is None or jnp.isfinite(s.loss) for s in stats
        )


class TestOptimizerHygiene:
    def test_grad_clip_bounds_update_norm(self):
        """clip_by_global_norm chained before SGD: a huge gradient must
        produce an update whose global norm is lr * clip."""
        import optax

        cfg = TrainConfig(optimizer="sgd", learning_rate=1.0,
                          grad_clip_norm=1.0)
        tx = cfg.make_optimizer()
        p = {"w": jnp.zeros((4,))}
        g = {"w": jnp.full((4,), 100.0)}  # norm 200
        updates, _ = tx.update(g, tx.init(p), p)
        norm = float(optax.global_norm(updates))
        assert abs(norm - 1.0) < 1e-4  # momentum=0.9 SGD: first step = g

    def test_decay_mask_spares_rank1_params(self):
        """With decay_mask, zero-gradient biases/norm scales must not
        shrink, while kernels still decay."""
        cfg = TrainConfig(optimizer="adamw", learning_rate=0.1,
                          weight_decay=0.5, decay_mask=True)
        tx = cfg.make_optimizer()
        p = {"kernel": jnp.ones((2, 2)), "bias": jnp.ones((2,))}
        g = {"kernel": jnp.zeros((2, 2)), "bias": jnp.zeros((2,))}
        updates, _ = tx.update(g, tx.init(p), p)
        assert float(jnp.abs(updates["bias"]).max()) == 0.0
        assert float(jnp.abs(updates["kernel"]).max()) > 0.0

    def test_defaults_keep_checkpoint_structure(self):
        """Defaults-off must produce the identical optimizer-state pytree
        as before these knobs existed (resume compatibility)."""
        import optax

        p = {"w": jnp.ones((2,))}
        old = optax.adamw(1e-3, weight_decay=1e-4).init(p)
        new = TrainConfig().make_optimizer().init(p)
        assert (
            jax.tree_util.tree_structure(old)
            == jax.tree_util.tree_structure(new)
        )

    def test_decay_mask_rejects_sgd(self):
        import pytest as _pytest

        cfg = TrainConfig(optimizer="sgd", decay_mask=True)
        with _pytest.raises(ValueError, match="requires the adamw"):
            cfg.make_optimizer()


class TestFusedData:
    """param.data=fused — batch generation inlined into the jitted train
    step (Trainer sample_fn): one dispatch per step, zero per-step host
    traffic. The hermetic-benchmark mode (PERF.md findings 3-4)."""

    def _train(self, cpus, sample_fn=None, batches=None, steps=3):
        from itertools import repeat

        with jax.default_device(cpus[0]):
            mesh = mesh_for_devices(cpus)
            m = MLP(features=(32,))
            params = m.init(
                jax.random.PRNGKey(0), jnp.zeros((1, 28, 28, 1))
            )["params"]
            tr = Trainer(
                lambda p, x: m.apply({"params": p}, x), params, mesh,
                TrainConfig(optimizer="sgd"),
                sample_fn=sample_fn,
            )
            stats = tr.run(
                batches if batches is not None else repeat({}), steps
            )
            return [s.loss for s in stats]

    def test_fused_stream_equals_device_stream(self, cpus):
        """fold_in(key, state.step) must reproduce device_batches'
        fold_in(key, i) stream exactly — fused is a dispatch-count
        optimization, not a different data distribution."""
        fused = self._train(cpus, sample_fn=datasets.mnist_sample(8))
        dev = self._train(
            cpus, batches=datasets.device_mnist_batches(8)
        )
        assert fused == dev

    def test_fused_entrypoint_runs(self, cpus):
        """The param.data=fused surface end to end through the runner
        context (mnist entrypoint)."""
        from cron_operator_tpu.backends.registry import resolve_entrypoint

        ctx_progress = {}

        class Ctx:
            params = {"steps": "2", "batch_size": "8", "platform": "cpu",
                      "data": "fused", "save_every": "0",
                      "flops_accounting": "1"}
            progress = ctx_progress
            publish = None
            should_stop = None
            namespace = "default"
            name = "fused-test"

        resolve_entrypoint("mnist")(Ctx())
        assert ctx_progress["steps_done"] == 2
        assert ctx_progress["last_loss"] is not None
        assert ctx_progress.get("xla_flops_per_step")


class TestStepsPerCall:
    """steps_per_call: K optimizer steps per dispatched program (a
    lax.scan of the step body over fused data) — the host-roundtrip
    amortizer. Must be a pure dispatch-count change: same data stream,
    same final parameters."""

    def _final_checksum(self, cpus, steps, spc):
        from itertools import repeat

        with jax.default_device(cpus[0]):
            mesh = mesh_for_devices(cpus)
            m = MLP(features=(32,))
            params = m.init(
                jax.random.PRNGKey(0), jnp.zeros((1, 28, 28, 1))
            )["params"]
            tr = Trainer(
                lambda p, x: m.apply({"params": p}, x), params, mesh,
                TrainConfig(optimizer="sgd", steps_per_call=spc),
                sample_fn=datasets.mnist_sample(8),
            )
            stats = tr.run(repeat({}), steps)
            assert tr.steps_done == steps
            leaves = jax.tree_util.tree_leaves(tr.state.params)
            return (
                [s.step for s in stats],
                float(sum(jnp.sum(jnp.abs(l)) for l in leaves)),
            )

    def test_chunked_matches_unchunked(self, cpus):
        steps1, c1 = self._final_checksum(cpus, steps=6, spc=1)
        steps3, c3 = self._final_checksum(cpus, steps=6, spc=3)
        assert steps1 == [1, 2, 3, 4, 5, 6]
        assert steps3 == [3, 6]
        assert c1 == c3  # bit-identical params: same stream, fewer calls

    def test_partial_final_chunk(self, cpus):
        steps, c = self._final_checksum(cpus, steps=7, spc=3)
        assert steps == [3, 6, 7]  # 3 + 3 + partial 1
        _, c1 = self._final_checksum(cpus, steps=7, spc=1)
        assert c == c1

    def test_external_chunked_construction_ok(self, cpus):
        """steps_per_call > 1 without a fused sample_fn is legal now:
        run() scans over STACKED external batches (put_chunk), so
        construction must not reject the combination. Only feeding a
        single un-stacked batch through step(chunk>1) is an error
        (next test) — that path would replay one batch K times."""
        with jax.default_device(cpus[0]):
            mesh = mesh_for_devices(cpus)
            m = MLP(features=(32,))
            params = m.init(
                jax.random.PRNGKey(0), jnp.zeros((1, 28, 28, 1))
            )["params"]
            tr = Trainer(
                lambda p, x: m.apply({"params": p}, x), params, mesh,
                TrainConfig(optimizer="sgd", steps_per_call=4),
            )
            assert tr.resolved_steps_per_call == 4

    def test_step_chunk_requires_fused_data_too(self, cpus):
        """The public step(chunk=) path must hit the same guard as
        config.steps_per_call — otherwise one external batch silently
        replays through the whole scan."""
        import pytest

        with jax.default_device(cpus[0]):
            mesh = mesh_for_devices(cpus)
            m = MLP(features=(32,))
            params = m.init(
                jax.random.PRNGKey(0), jnp.zeros((1, 28, 28, 1))
            )["params"]
            tr = Trainer(
                lambda p, x: m.apply({"params": p}, x), params, mesh,
                TrainConfig(optimizer="sgd"),
            )
            batch = next(datasets.mnist_batches(8))
            with pytest.raises(ValueError, match="fused data"):
                tr.step(batch, chunk=4)


class TestScanChainedExternal:
    """External-data scan chaining (the PR-12 executor default): run()
    stacks K real host batches (put_chunk) and scans over the stacked
    chunk — a pure dispatch-count change. Params must be BIT-exact
    against steps_per_call=1 on the same stream, published losses within
    1 ulp, and the per-step stats timeline must stay dense (the
    step-phase profiler and rolling MFU consume it)."""

    def _run(self, cpus, steps, spc, stage_async=False, store=None,
             save_every=0):
        with jax.default_device(cpus[0]):
            mesh = mesh_for_devices(cpus)
            m = MLP(features=(32,))
            params = m.init(
                jax.random.PRNGKey(0), jnp.zeros((1, 28, 28, 1))
            )["params"]
            tr = Trainer(
                lambda p, x: m.apply({"params": p}, x), params, mesh,
                TrainConfig(optimizer="sgd", steps_per_call=spc,
                            stage_async=stage_async,
                            save_every=save_every),
                checkpoint=store,
            )
            per_step = []
            stats = tr.run(datasets.mnist_batches(16, seed=21), steps,
                           on_step=per_step.append)
            if store is not None:
                store.close()
            return tr, stats, per_step

    @staticmethod
    def _leaves(tr):
        import numpy as np

        return [
            np.asarray(leaf)
            for leaf in jax.tree_util.tree_leaves(tr.state.params)
        ]

    @pytest.mark.parametrize("spc", [2, 5])
    def test_bit_exact_params_and_ulp_losses(self, cpus, spc):
        import numpy as np

        ref, _, ref_steps = self._run(cpus, steps=7, spc=1)
        tr, stats, _ = self._run(cpus, steps=7, spc=spc)
        for a, b in zip(self._leaves(ref), self._leaves(tr)):
            assert np.array_equal(a, b)  # bit-exact, not allclose
        # Each dispatch publishes its chunk-final loss; it must match
        # the per-step path's loss at that step to 1 ulp.
        ref_loss = {s.step: s.loss for s in ref_steps
                    if s.loss is not None}
        for s in stats:
            np.testing.assert_array_max_ulp(
                np.float32(s.loss), np.float32(ref_loss[s.step]),
                maxulp=1,
            )

    def test_per_step_emission_stays_dense(self, cpus):
        _, stats, per_step = self._run(cpus, steps=7, spc=5)
        assert [s.step for s in stats] == [5, 7]  # dispatch-level
        assert [s.step for s in per_step] == [1, 2, 3, 4, 5, 6, 7]
        assert all(s.chunk == 1 for s in per_step)
        assert all(s.step_time_s > 0 for s in per_step)
        # loss rides the chunk-final step only (the one fetched)
        assert [s.step for s in per_step
                if s.loss is not None] == [5, 7]

    def test_save_every_snaps_chunks(self, cpus, tmp_path):
        """Chunks must not straddle a save_every multiple: the snapped
        schedule for spc=5 over 7 steps at save_every=3 is [3, 3, 1],
        saves land on their exact steps, and the math stays bit-exact
        vs the unchunked uncheckpointed run."""
        import numpy as np

        from cron_operator_tpu.workloads.checkpoint import CheckpointStore

        store = CheckpointStore("ns", "chain-1785339000",
                                root=str(tmp_path))
        tr, stats, _ = self._run(cpus, steps=7, spc=5, store=store,
                                 save_every=3)
        assert [s.step for s in stats] == [3, 6, 7]
        reopened = CheckpointStore("ns", "chain-1785339000",
                                   root=str(tmp_path), create=False)
        assert reopened.latest_step() == 6
        reopened.close()
        ref, _, _ = self._run(cpus, steps=7, spc=1)
        for a, b in zip(self._leaves(ref), self._leaves(tr)):
            assert np.array_equal(a, b)

    def test_async_stager_bit_exact(self, cpus):
        """The background ChunkStager must deliver the exact batches the
        synchronous path stages — identical final params, and "auto"
        resolves to the documented chunk length. Single-device mesh: the
        stager only arms there (see test_multi_device_stages_inline)."""
        import numpy as np

        a, _, _ = self._run(cpus[:1], steps=11, spc="auto",
                            stage_async=False)
        b, _, _ = self._run(cpus[:1], steps=11, spc="auto",
                            stage_async=True)
        assert a.resolved_steps_per_call == 8
        for x, y in zip(self._leaves(a), self._leaves(b)):
            assert np.array_equal(x, y)

    def test_multi_device_stages_inline(self, cpus, monkeypatch):
        """Deadlock gate: on a >1-device mesh the staging thread would be
        a second program dispatcher racing the step program's collectives
        across the per-device queues (XLA rendezvous deadlock — observed
        as a wedged training thread surviving preempt/stop). stage_async
        must silently degrade to inline staging there, never spawn the
        ChunkStager."""
        if len(cpus) < 2:
            pytest.skip("needs a multi-device mesh")
        from cron_operator_tpu.workloads import data as data_mod

        def _forbidden(*a, **k):
            raise AssertionError(
                "ChunkStager spawned on a multi-device mesh"
            )

        monkeypatch.setattr(data_mod, "ChunkStager", _forbidden)
        monkeypatch.setattr(data_mod, "Prefetcher", _forbidden)
        tr, _, per_step = self._run(cpus, steps=6, spc=3,
                                    stage_async=True)
        assert tr._staging_devices() == len(cpus)
        assert [s.step for s in per_step] == list(range(1, 7))


class TestStepperLRU:
    def test_hit_refreshes_recency(self, cpus):
        """The fused _multi cache is an LRU, not FIFO: a snapped
        schedule alternates steady and boundary/tail lengths, so a hit
        must re-protect the entry — FIFO eviction would recompile the
        steady program on every other dispatch once the cap was hit."""
        with jax.default_device(cpus[0]):
            mesh = mesh_for_devices(cpus)
            m = MLP(features=(32,))
            params = m.init(
                jax.random.PRNGKey(0), jnp.zeros((1, 28, 28, 1))
            )["params"]
            tr = Trainer(
                lambda p, x: m.apply({"params": p}, x), params, mesh,
                TrainConfig(optimizer="sgd"),
                sample_fn=datasets.mnist_sample(8),
            )
            tr._multi_cap = 2
            f2 = tr._stepper(2)
            f3 = tr._stepper(3)
            assert tr._stepper(2) is f2  # hit — must refresh recency
            tr._stepper(4)  # cap hit: must evict 3 (stale), not 2
            assert set(tr._multi) == {2, 4}
            assert tr._stepper(2) is f2
            assert tr._stepper(3) is not f3  # was evicted, rebuilt


@pytest.mark.slow  # re-exec without a platform pin makes jax's TPU init
# retry GCP metadata for minutes on hosts with libtpu but no TPU
class TestTpuProbeSelfHeal:
    def test_stale_platform_pin_heals_to_registered_backend(self):
        """JAX_PLATFORMS naming an unregistered platform must re-exec
        with the pin cleared and report cleared_jax_platforms (bench.py
        strips the pin for all later children on that signal) — not fail
        rc=2 and silently downgrade the artifact to CPU."""
        import json
        import os
        import subprocess
        import sys
        from pathlib import Path

        probe = (Path(__file__).resolve().parent.parent
                 / "hack" / "tpu_probe.py")
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "no-such-platform"
        env.pop("TPU_PROBE_REEXEC", None)
        env.pop("TPU_PROBE_HOLD", None)  # would block on stdin after OK
        # Strip any plugin site-paths so only built-in backends register
        # (deterministic regardless of the host's tunnel plugins).
        env["PYTHONPATH"] = str(probe.parent.parent)
        out = subprocess.run(
            [sys.executable, str(probe)], env=env,
            capture_output=True, text=True, timeout=180,
            stdin=subprocess.DEVNULL,
        )
        assert out.returncode == 0, out.stderr[-500:]
        rec = json.loads(out.stdout.strip().splitlines()[-1])
        assert rec["ok"] is True
        assert rec["cleared_jax_platforms"] is True
        assert rec["backend"]  # whatever actually registered (cpu here)
