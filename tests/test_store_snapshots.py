"""Copy-on-write snapshot isolation and index routing in the APIServer.

The mutation-isolation guard of the PR: ``list()`` and watch events hand
out SHARED frozen snapshots, so a buggy caller that tries to mutate one
must get ``TypeError`` — and the store must be provably uncorrupted
afterwards. ``deepcopy``/``thaw`` stay the sanctioned escape hatch.
Index tests pin the owner-UID / label / namespace routing that makes
``list`` and cascade GC proportional to their result sets.
"""

import copy
import json
import threading

import pytest

from cron_operator_tpu.runtime.frozen import FrozenDict, FrozenList, freeze, thaw
from cron_operator_tpu.runtime.kube import APIServer, WatchEvent


def job(name, ns="default", labels=None, owners=None):
    meta = {"name": name, "namespace": ns}
    if labels:
        meta["labels"] = dict(labels)
    if owners:
        meta["ownerReferences"] = owners
    return {
        "apiVersion": "kubeflow.org/v1",
        "kind": "JAXJob",
        "metadata": meta,
        "spec": {"replicaSpecs": {"Worker": {"replicas": 1}}},
    }


def owner_ref(obj, controller=True):
    meta = obj["metadata"]
    return {
        "apiVersion": obj["apiVersion"],
        "kind": obj["kind"],
        "name": meta["name"],
        "uid": meta["uid"],
        "controller": controller,
    }


class TestSnapshotIsolation:
    def test_list_snapshot_refuses_mutation_everywhere(self, api):
        api.create(job("a", labels={"app": "x"}))
        snap = api.list("kubeflow.org/v1", "JAXJob")[0]
        with pytest.raises(TypeError):
            snap["status"] = {"phase": "Hacked"}
        with pytest.raises(TypeError):
            snap["metadata"]["labels"]["app"] = "evil"
        with pytest.raises(TypeError):
            del snap["spec"]
        with pytest.raises(TypeError):
            snap.update({"kind": "Other"})
        # The store is untouched by every failed attempt.
        obj = api.get("kubeflow.org/v1", "JAXJob", "default", "a")
        assert "status" not in obj
        assert obj["metadata"]["labels"] == {"app": "x"}

    def test_nested_lists_frozen_too(self, api):
        o = job("a")
        o["spec"]["containers"] = [{"name": "c", "args": ["x"]}]
        api.create(o)
        snap = api.list("kubeflow.org/v1", "JAXJob")[0]
        with pytest.raises(TypeError):
            snap["spec"]["containers"].append({})
        with pytest.raises(TypeError):
            snap["spec"]["containers"][0]["args"][0] = "y"

    def test_watch_event_object_is_frozen(self, api):
        events = []
        api.add_watcher(events.append)
        api.create(job("a"))
        assert api.flush()
        ev: WatchEvent = events[0]
        with pytest.raises(TypeError):
            ev.object["metadata"]["name"] = "b"
        # Every subscriber shares ONE committed snapshot with the store.
        assert ev.object is api.list("kubeflow.org/v1", "JAXJob")[0]

    def test_deepcopy_thaws_to_private_mutable_copy(self, api):
        api.create(job("a", labels={"app": "x"}))
        snap = api.list("kubeflow.org/v1", "JAXJob")[0]
        mine = copy.deepcopy(snap)
        assert type(mine) is dict
        mine["metadata"]["labels"]["app"] = "mine"
        mine["status"] = {"phase": "Running"}
        fresh = api.list("kubeflow.org/v1", "JAXJob")[0]
        assert fresh["metadata"]["labels"]["app"] == "x"
        assert "status" not in fresh

    def test_get_returns_mutable_read_modify_write_copy(self, api):
        api.create(job("a"))
        obj = api.get("kubeflow.org/v1", "JAXJob", "default", "a")
        obj["spec"]["replicaSpecs"]["Worker"]["replicas"] = 4
        api.update(obj)
        assert api.list("kubeflow.org/v1", "JAXJob")[0]["spec"][
            "replicaSpecs"]["Worker"]["replicas"] == 4

    def test_snapshot_survives_later_writes(self, api):
        api.create(job("a"))
        before = api.list("kubeflow.org/v1", "JAXJob")[0]
        rv = before["metadata"]["resourceVersion"]
        obj = api.get("kubeflow.org/v1", "JAXJob", "default", "a")
        obj["spec"]["replicaSpecs"]["Worker"]["replicas"] = 8
        api.update(obj)
        # The old snapshot is a committed version: stable forever.
        assert before["metadata"]["resourceVersion"] == rv
        assert before["spec"]["replicaSpecs"]["Worker"]["replicas"] == 1

    def test_snapshots_json_serializable(self, api):
        api.create(job("a", labels={"app": "x"}))
        snap = api.list("kubeflow.org/v1", "JAXJob")[0]
        assert json.loads(json.dumps(snap))["metadata"]["name"] == "a"


class TestFrozenPrimitives:
    def test_freeze_shares_already_frozen_subtrees(self):
        inner = freeze({"a": [1, 2]})
        outer = freeze({"inner": inner})
        assert outer["inner"] is inner

    def test_thaw_round_trip(self):
        src = {"a": {"b": [1, {"c": 2}]}}
        plain = thaw(freeze(src))
        assert plain == src
        assert type(plain["a"]["b"]) is list
        assert type(plain["a"]["b"][1]) is dict

    def test_frozen_types_still_behave_like_builtins(self):
        d = freeze({"a": 1})
        l = freeze([1, 2])
        assert isinstance(d, dict) and isinstance(l, list)
        assert d == {"a": 1} and l == [1, 2]
        assert FrozenDict is type(d) and FrozenList is type(l)


class TestIndexedRouting:
    def test_dependents_served_from_owner_index(self, api):
        owner = api.create(job("owner"))
        for i in range(3):
            api.create(job(f"child-{i}", owners=[owner_ref(owner)]))
        api.create(job("stranger"))
        uid = owner["metadata"]["uid"]
        deps = api.dependents(uid)
        assert sorted(d["metadata"]["name"] for d in deps) == [
            "child-0", "child-1", "child-2"]
        assert api.dependents(uid, namespace="other") == []
        assert api.dependents(None) == []

    def test_list_by_owner_uid(self, api):
        owner = api.create(job("owner"))
        api.create(job("child", owners=[owner_ref(owner)]))
        api.create(job("stranger"))
        out = api.list("kubeflow.org/v1", "JAXJob",
                       owner_uid=owner["metadata"]["uid"])
        assert [o["metadata"]["name"] for o in out] == ["child"]

    def test_owner_index_follows_updates(self, api):
        owner = api.create(job("owner"))
        child = api.create(job("child", owners=[owner_ref(owner)]))
        uid = owner["metadata"]["uid"]
        assert len(api.dependents(uid)) == 1
        child["metadata"]["ownerReferences"] = []
        api.update(child)
        assert api.dependents(uid) == []

    def test_cascade_delete_via_index_reaches_grandchildren(self, api):
        owner = api.create(job("owner"))
        child = api.create(job("child", owners=[owner_ref(owner)]))
        api.create(job("grandchild", owners=[owner_ref(child)]))
        api.create(job("stranger"))
        api.delete("kubeflow.org/v1", "JAXJob", "default", "owner")
        names = [o["metadata"]["name"]
                 for o in api.list("kubeflow.org/v1", "JAXJob")]
        assert names == ["stranger"]

    def test_label_index_follows_label_edits(self, api):
        api.create(job("a", labels={"app": "x"}))
        sel = {"app": "x"}
        assert len(api.list("kubeflow.org/v1", "JAXJob",
                            label_selector=sel)) == 1
        obj = api.get("kubeflow.org/v1", "JAXJob", "default", "a")
        obj["metadata"]["labels"] = {"app": "y"}
        api.update(obj)
        assert api.list("kubeflow.org/v1", "JAXJob",
                        label_selector=sel) == []
        assert len(api.list("kubeflow.org/v1", "JAXJob",
                            label_selector={"app": "y"})) == 1

    def test_multi_key_selector_requires_all_pairs(self, api):
        api.create(job("a", labels={"app": "x", "tier": "web"}))
        api.create(job("b", labels={"app": "x"}))
        out = api.list("kubeflow.org/v1", "JAXJob",
                       label_selector={"app": "x", "tier": "web"})
        assert [o["metadata"]["name"] for o in out] == ["a"]

    def test_namespace_index_isolates_namespaces(self, api):
        api.create(job("a", ns="ns1"))
        api.create(job("b", ns="ns2"))
        out = api.list("kubeflow.org/v1", "JAXJob", namespace="ns1")
        assert [o["metadata"]["name"] for o in out] == ["a"]
        assert len(api.list("kubeflow.org/v1", "JAXJob")) == 2

    def test_indexes_consistent_under_concurrent_churn(self, api):
        owner = api.create(job("owner"))
        errs = []

        def churn(k):
            try:
                for i in range(30):
                    name = f"c{k}-{i}"
                    api.create(job(name, owners=[owner_ref(owner)],
                                   labels={"batch": f"b{k}"}))
                    if i % 3 == 0:
                        api.delete("kubeflow.org/v1", "JAXJob",
                                   "default", name)
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        threads = [threading.Thread(target=churn, args=(k,))
                   for k in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert not errs
        uid = owner["metadata"]["uid"]
        live = {o["metadata"]["name"]
                for o in api.list("kubeflow.org/v1", "JAXJob")} - {"owner"}
        assert {d["metadata"]["name"] for d in api.dependents(uid)} == live
        by_label = {
            o["metadata"]["name"]
            for k in range(4)
            for o in api.list("kubeflow.org/v1", "JAXJob",
                              label_selector={"batch": f"b{k}"})
        }
        assert by_label == live
