"""End-to-end chaos soak specs.

Runs ``hack/chaos_soak.py`` in-process at small N: the hardened
operator must hold all five invariants under the seeded fault storm,
and the same storm against the un-hardened configuration (single-shot
writes, no watch resync) must demonstrably violate at least one —
the regression the chaos layer exists to catch.

Crash mode adds kill+restart rounds on top of the storm: the durable
(WAL + snapshot) configuration must additionally hold I6 (recovered
state == independent WAL replay) and I7 (no tick fires twice across a
restart, no in-window tick permanently lost), while the same kill
schedule WITHOUT durability must demonstrably violate I7."""

import importlib.util
import pathlib

import pytest

_SOAK_PATH = (
    pathlib.Path(__file__).resolve().parent.parent / "hack" / "chaos_soak.py"
)


def _load_soak():
    spec = importlib.util.spec_from_file_location("chaos_soak", _SOAK_PATH)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def soak():
    return _load_soak()


class TestHardenedSoak:
    def test_all_invariants_hold_under_chaos(self, soak):
        chaotic = soak.run_soak(seed=7, n_crons=12, rounds=3)
        replay = soak.run_soak(seed=7, n_crons=12, rounds=3, chaotic=False)
        inv = soak.check_invariants(chaotic, replay, soak.HISTORY_LIMIT)
        failed = {k: v["detail"] for k, v in inv.items() if not v["ok"]}
        assert not failed, failed
        # the storm actually stormed — faults of several classes landed
        assert chaotic["faults_injected"]
        assert sum(chaotic["faults_injected"].values()) > 0
        assert replay["faults_injected"] == {}

    def test_schedule_determinism_across_expansions(self, soak):
        from cron_operator_tpu.runtime.faults import FaultPlan

        a = FaultPlan.default_chaos(7)
        b = FaultPlan.default_chaos(7)
        assert a.schedule(6) == b.schedule(6)
        assert a.trace_hash(6) == b.trace_hash(6)


class TestCrashRestartSoak:
    def test_invariants_hold_across_kill_restart(self, soak):
        chaotic = soak.run_soak(seed=7, n_crons=12, rounds=4, crash=True)
        replay = soak.run_soak(
            seed=7, n_crons=12, rounds=4, chaotic=False, crash=True
        )
        inv = soak.check_invariants(chaotic, replay, soak.HISTORY_LIMIT)
        failed = {k: v["detail"] for k, v in inv.items() if not v["ok"]}
        assert not failed, failed
        # The kill schedule actually killed, and recovery actually ran.
        assert chaotic["kills"], "crash mode scheduled no kills"
        assert "I6_recovery_equals_replay" in inv
        assert "I7_restart_tick_integrity" in inv
        for k in chaotic["kills"]:
            assert k["i6_recovery_equals_replay"], k

    def test_kill_schedule_is_deterministic(self, soak):
        a = soak.run_soak(seed=11, n_crons=8, rounds=4, crash=True)
        b = soak.run_soak(seed=11, n_crons=8, rounds=4, crash=True)
        assert [k["point"] for k in a["kills"]] == [
            k["point"] for k in b["kills"]
        ]
        assert a["fault_trace_hash"] == b["fault_trace_hash"]

    def test_no_durability_violates_restart_integrity(self, soak):
        chaotic = soak.run_soak(
            seed=7, n_crons=12, rounds=4, crash=True, durability=False
        )
        replay = soak.run_soak(
            seed=7, n_crons=12, rounds=4, chaotic=False, crash=True
        )
        inv = soak.check_invariants(chaotic, replay, soak.HISTORY_LIMIT)
        assert not inv["I7_restart_tick_integrity"]["ok"], (
            "restarting from an empty data dir held I7 — the soak no "
            "longer demonstrates the loss the WAL exists to prevent"
        )


class TestUnhardenedSoak:
    def test_unhardened_operator_violates_an_invariant(self, soak):
        chaotic = soak.run_soak(seed=7, n_crons=40, rounds=4, unhardened=True)
        replay = soak.run_soak(
            seed=7, n_crons=40, rounds=4, chaotic=False, unhardened=True
        )
        inv = soak.check_invariants(chaotic, replay, soak.HISTORY_LIMIT)
        violated = [k for k, v in inv.items() if not v["ok"]]
        assert violated, (
            "un-hardened run held all invariants — the chaos layer no "
            "longer demonstrates the failure modes the hardening prevents"
        )
