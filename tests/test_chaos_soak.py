"""End-to-end chaos soak specs.

Runs ``hack/chaos_soak.py`` in-process at small N: the hardened
operator must hold all five invariants under the seeded fault storm,
and the same storm against the un-hardened configuration (single-shot
writes, no watch resync) must demonstrably violate at least one —
the regression the chaos layer exists to catch."""

import importlib.util
import pathlib

import pytest

_SOAK_PATH = (
    pathlib.Path(__file__).resolve().parent.parent / "hack" / "chaos_soak.py"
)


def _load_soak():
    spec = importlib.util.spec_from_file_location("chaos_soak", _SOAK_PATH)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def soak():
    return _load_soak()


class TestHardenedSoak:
    def test_all_invariants_hold_under_chaos(self, soak):
        chaotic = soak.run_soak(seed=7, n_crons=12, rounds=3)
        replay = soak.run_soak(seed=7, n_crons=12, rounds=3, chaotic=False)
        inv = soak.check_invariants(chaotic, replay, soak.HISTORY_LIMIT)
        failed = {k: v["detail"] for k, v in inv.items() if not v["ok"]}
        assert not failed, failed
        # the storm actually stormed — faults of several classes landed
        assert chaotic["faults_injected"]
        assert sum(chaotic["faults_injected"].values()) > 0
        assert replay["faults_injected"] == {}

    def test_schedule_determinism_across_expansions(self, soak):
        from cron_operator_tpu.runtime.faults import FaultPlan

        a = FaultPlan.default_chaos(7)
        b = FaultPlan.default_chaos(7)
        assert a.schedule(6) == b.schedule(6)
        assert a.trace_hash(6) == b.trace_hash(6)


class TestUnhardenedSoak:
    def test_unhardened_operator_violates_an_invariant(self, soak):
        chaotic = soak.run_soak(seed=7, n_crons=40, rounds=4, unhardened=True)
        replay = soak.run_soak(
            seed=7, n_crons=40, rounds=4, chaotic=False, unhardened=True
        )
        inv = soak.check_invariants(chaotic, replay, soak.HISTORY_LIMIT)
        violated = [k for k, v in inv.items() if not v["ok"]]
        assert violated, (
            "un-hardened run held all invariants — the chaos layer no "
            "longer demonstrates the failure modes the hardening prevents"
        )
