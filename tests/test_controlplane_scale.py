"""Control-plane scale smoke: a full Manager + reconciler sweep over
hundreds of Crons on a fake clock must complete promptly, error-free,
and cascade-GC correctly. The 5k sweep mirrors ``make bench-controlplane``
and is ``slow``-marked (excluded from the tier-1 gate).
"""

import threading
import time

import pytest

from cron_operator_tpu.api.scheme import GVK_CRON, default_scheme
from cron_operator_tpu.controller import CronReconciler
from cron_operator_tpu.runtime import APIServer, Manager
from cron_operator_tpu.utils.clock import FakeClock
from datetime import timedelta

CRON_AV = "apps.kubedl.io/v1alpha1"
WORKLOAD_AV = "kubeflow.org/v1"


def cron(i):
    return {
        "apiVersion": CRON_AV,
        "kind": "Cron",
        "metadata": {"name": f"scale-{i}", "namespace": "default"},
        "spec": {
            "schedule": f"{i % 60} * * * *" if i % 2 == 0 else "@every 3600s",
            "concurrencyPolicy": "Allow",
            "template": {"workload": {
                "apiVersion": WORKLOAD_AV,
                "kind": "JAXJob",
                "spec": {"replicaSpecs": {"Worker": {"replicas": 1}}},
            }},
        },
    }


def _sweep(n_crons, timeout_s):
    """Create N Crons, make every tick due, run the real manager until
    each Cron has created its workload. Returns (api, mgr, elapsed)."""
    clock = FakeClock()
    api = APIServer(clock=clock)
    for i in range(n_crons):
        api.create(cron(i))

    created = threading.Semaphore(0)
    api.add_watcher(
        lambda ev: created.release()
        if ev.type == "ADDED" and ev.object.get("kind") == "JAXJob"
        else None
    )
    mgr = Manager(api, max_concurrent_reconciles=8)
    rec = CronReconciler(api, metrics=mgr.metrics)
    mgr.add_controller("cron", rec.reconcile, for_gvk=GVK_CRON,
                       owns=default_scheme().workload_kinds())
    clock.advance(timedelta(minutes=61))

    t0 = time.monotonic()
    mgr.start()
    deadline = t0 + timeout_s
    done = 0
    while done < n_crons and time.monotonic() < deadline:
        if created.acquire(timeout=0.5):
            done += 1
    elapsed = time.monotonic() - t0
    assert done == n_crons, f"only {done}/{n_crons} workloads in {elapsed:.1f}s"
    return api, mgr, elapsed


def _finish(api, mgr):
    errs = mgr.metrics.get(
        'controller_runtime_reconcile_errors_total{controller="cron"}')
    mgr.stop()
    api.close()
    assert errs == 0, f"{errs} reconcile errors during sweep"


class TestScaleSmoke:
    def test_300_cron_sweep_and_cascade_gc(self):
        api, mgr, _ = _sweep(300, timeout_s=60.0)
        try:
            workloads = api.list(WORKLOAD_AV, "JAXJob", namespace="default")
            assert len(workloads) == 300
            # Every workload is owner-indexed to its Cron; deleting the
            # Cron cascades through the index.
            c = api.get(CRON_AV, "Cron", "default", "scale-0")
            uid = c["metadata"]["uid"]
            assert len(api.dependents(uid)) == 1
            api.delete(CRON_AV, "Cron", "default", "scale-0")
            assert api.dependents(uid) == []
            assert len(api.list(WORKLOAD_AV, "JAXJob",
                                namespace="default")) == 299
        finally:
            _finish(api, mgr)

    @pytest.mark.slow
    def test_5k_cron_sweep(self):
        api, mgr, elapsed = _sweep(5000, timeout_s=600.0)
        try:
            assert len(api.list(WORKLOAD_AV, "JAXJob",
                                namespace="default")) == 5000
            # Sanity floor, not a benchmark: the indexed store must keep
            # a 5k sweep comfortably inside the timeout.
            assert elapsed < 300.0
        finally:
            _finish(api, mgr)
