"""Lying-network injection (runtime/netfaults.py) and the transport
hardening that survives it (PR 20's tentpole).

Two halves:

- the injector itself: PRF determinism (one seed → one schedule),
  transparent relay when no faults are planned, and each fault kind
  doing what the label says (duplicate, slow-drip, RST, partition);
- the transport under the injector: duplicated frames become counted
  no-ops (the seq ledger), slow-dripped frames reassemble whole, a
  one-way blackhole is detected within the heartbeat timeout on
  whichever side went deaf — and WITHOUT heartbeats the same blackhole
  wedges the link silently, which is the counter-proof the chaos soak
  automates.
"""

import shutil
import socket
import tempfile
import threading
import time
import unittest

from cron_operator_tpu.runtime.faults import (
    NET_FAULT_KINDS,
    LinkPlan,
    NetworkFaultInjector,
)
from cron_operator_tpu.runtime.kube import APIServer
from cron_operator_tpu.runtime.persistence import Persistence
from cron_operator_tpu.runtime.shard import FollowerReplica, canonical_state
from cron_operator_tpu.runtime.transport import (
    RetryBudget,
    ShipFollower,
    WALShipServer,
)
from cron_operator_tpu.utils.clock import FakeClock, RealClock


def _obj(name: str, ns: str = "default") -> dict:
    return {
        "apiVersion": "kubeflow.org/v1",
        "kind": "JAXJob",
        "metadata": {"name": name, "namespace": ns},
        "spec": {"replicaSpecs": {"Worker": {"replicas": 1}}},
    }


def _wait(predicate, timeout=5.0, interval=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return predicate()


class _TmpDirTest(unittest.TestCase):
    def setUp(self):
        self.dir = tempfile.mkdtemp(prefix="netfaults-test-")
        self.addCleanup(shutil.rmtree, self.dir, ignore_errors=True)


class _Echo:
    """Minimal TCP echo server (one connection at a time is plenty)."""

    def __init__(self):
        self.listener = socket.create_server(("127.0.0.1", 0))
        self.listener.settimeout(0.2)
        self.port = self.listener.getsockname()[1]
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    def _serve(self):
        while not self._stop.is_set():
            try:
                sock, _ = self.listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(
                target=self._echo, args=(sock,), daemon=True
            ).start()

    def _echo(self, sock):
        try:
            while True:
                data = sock.recv(65536)
                if not data:
                    return
                sock.sendall(data)
        except OSError:
            pass
        finally:
            try:
                sock.close()
            except OSError:
                pass

    def close(self):
        self._stop.set()
        try:
            self.listener.close()
        except OSError:
            pass


class TestInjectorPRF(unittest.TestCase):
    def test_same_seed_same_decisions(self):
        a = NetworkFaultInjector(seed=42)
        b = NetworkFaultInjector(seed=42)
        for kind in NET_FAULT_KINDS:
            for idx in range(50):
                self.assertEqual(
                    a.fraction("ship", "c2s", 1, idx, kind),
                    b.fraction("ship", "c2s", 1, idx, kind),
                )

    def test_different_seed_different_schedule(self):
        a = NetworkFaultInjector(seed=42)
        b = NetworkFaultInjector(seed=43)
        sched_a = a.schedule(rounds=12, links=["ship", "api"])
        sched_b = b.schedule(rounds=12, links=["ship", "api"])
        self.assertNotEqual(sched_a, sched_b)
        # And re-expanding from the same injector is stable.
        self.assertEqual(sched_a, a.schedule(rounds=12, links=["ship", "api"]))

    def test_schedule_shape(self):
        inj = NetworkFaultInjector(seed=7)
        sched = inj.schedule(rounds=20, links=["ship"])
        self.assertEqual(len(sched), 20)
        for entry in sched:
            self.assertEqual(entry["link"], "ship")
            self.assertIn(entry["direction"], ("c2s", "s2c", "both"))
            self.assertGreaterEqual(entry["hold_s"], 0.3)
            self.assertLessEqual(entry["hold_s"], 1.0)


class TestFaultProxy(unittest.TestCase):
    def setUp(self):
        self.echo = _Echo()
        self.addCleanup(self.echo.close)
        self.inj = NetworkFaultInjector(seed=1)
        self.addCleanup(self.inj.close)

    def _dial(self, port):
        sock = socket.create_connection(("127.0.0.1", port), timeout=2.0)
        sock.settimeout(2.0)
        self.addCleanup(sock.close)
        return sock

    def test_planless_proxy_is_transparent(self):
        proxy = self.inj.proxy("echo", "127.0.0.1", self.echo.port)
        sock = self._dial(proxy.port)
        for payload in (b"hello", b"x" * 10000):
            sock.sendall(payload)
            got = b""
            while len(got) < len(payload):
                got += sock.recv(65536)
            self.assertEqual(got, payload)
        self.assertEqual(self.inj.stats()["injected"]["blackhole"], 0)

    def test_upstream_refused_refuses_dialer(self):
        dead = socket.create_server(("127.0.0.1", 0))
        port = dead.getsockname()[1]
        dead.close()
        proxy = self.inj.proxy("dead", "127.0.0.1", port)
        sock = self._dial(proxy.port)  # accept() succeeds...
        # ...but the connection is torn down once the upstream refuses.
        sock.settimeout(2.0)
        self.assertEqual(sock.recv(1), b"")

    def test_partition_goes_dark_heal_admits_new_connections(self):
        proxy = self.inj.proxy("echo", "127.0.0.1", self.echo.port)
        sock = self._dial(proxy.port)
        sock.sendall(b"ping")
        self.assertEqual(sock.recv(65536), b"ping")

        self.inj.partition("echo")  # both directions
        sock.sendall(b"lost")
        with self.assertRaises(socket.timeout):
            sock.recv(65536)  # silence, not EOF: half-open by design

        self.inj.heal("echo")
        # The old connection is sticky-dark; a NEW one works.
        sock2 = self._dial(proxy.port)
        sock2.sendall(b"back")
        self.assertEqual(sock2.recv(65536), b"back")
        self.assertGreaterEqual(self.inj.stats()["injected"]["blackhole"], 1)

    def test_one_way_partition_other_direction_flows(self):
        proxy = self.inj.proxy("echo", "127.0.0.1", self.echo.port)
        self.inj.partition("echo", direction="s2c")
        sock = self._dial(proxy.port)
        sock.sendall(b"there")  # c2s still flows (echo server gets it)
        with self.assertRaises(socket.timeout):
            sock.recv(65536)  # the reply is eaten

    def test_rst_surfaces_as_connection_reset(self):
        plan = LinkPlan(p_rst=1.0)
        proxy = self.inj.proxy("echo", "127.0.0.1", self.echo.port,
                               plan=plan)
        sock = self._dial(proxy.port)
        try:
            sock.sendall(b"doomed")
            # First unit through the pump RSTs both ends.
            with self.assertRaises((ConnectionResetError, BrokenPipeError,
                                    ConnectionAbortedError)):
                for _ in range(20):
                    if sock.recv(65536) == b"":
                        raise ConnectionResetError  # EOF also acceptable
                    time.sleep(0.05)
        except socket.timeout:
            self.fail("RST never arrived")
        self.assertGreaterEqual(self.inj.stats()["injected"]["rst"], 1)


class TestTransportUnderFaults(_TmpDirTest):
    """WALShipServer ↔ ShipFollower through a framed FaultProxy."""

    # Tight heartbeat so detection tests run in ~1s, with timeout still
    # >> interval so a healthy-but-slow link never trips it.
    HB_INTERVAL = 0.1
    HB_TIMEOUT = 1.0

    def _leader(self, heartbeats=True):
        store = APIServer(clock=FakeClock())
        pers = Persistence(self.dir, fsync_every=1)
        pers.start(store)
        server = WALShipServer(
            pers, heartbeats=heartbeats,
            heartbeat_interval_s=self.HB_INTERVAL,
            heartbeat_timeout_s=self.HB_TIMEOUT,
        )
        self.addCleanup(server.close)
        return store, pers, server

    def _follower_via(self, proxy, heartbeats=True):
        replica = FollowerReplica(RealClock(), name="nf-test")
        follower = ShipFollower(
            "127.0.0.1", proxy.port, replica,
            heartbeats=heartbeats, heartbeat_timeout_s=self.HB_TIMEOUT,
        )
        self.addCleanup(follower.stop)
        return replica, follower

    def _injector(self, seed=11):
        inj = NetworkFaultInjector(seed=seed)
        self.addCleanup(inj.close)
        return inj

    def test_duplicated_frames_are_counted_noops(self):
        """Every WAL frame duplicated on the wire: the seq ledger drops
        each copy, the replica converges to the exact leader state —
        I13a's "no write doubled" under a frame-repeating middlebox."""
        store, pers, server = self._leader()
        inj = self._injector()
        proxy = inj.proxy("ship", "127.0.0.1", server.port, framed=True,
                          plan=LinkPlan(p_duplicate=1.0))
        replica, follower = self._follower_via(proxy)
        self.assertTrue(follower.wait_connected(5.0))
        for i in range(10):
            store.create(_obj(f"dup-{i}"))
        pers.flush()
        self.assertTrue(_wait(lambda: len(replica.store) == 10))
        self.assertTrue(_wait(lambda: follower.duplicate_frames >= 10))
        self.assertEqual(
            replica.state(),
            canonical_state(store.all_objects(), store._rv),
        )
        self.assertGreaterEqual(inj.stats()["injected"]["duplicate"], 10)

    def test_slowdripped_frames_reassemble_whole(self):
        """Every frame trickled 3 bytes at a time: framing reassembles,
        heartbeats don't fire (traffic IS flowing), state converges."""
        store, pers, server = self._leader()
        inj = self._injector()
        proxy = inj.proxy("ship", "127.0.0.1", server.port, framed=True,
                          plan=LinkPlan(p_slowdrip=1.0, drip_bytes=7,
                                        drip_pause_s=0.0005))
        replica, follower = self._follower_via(proxy)
        self.assertTrue(follower.wait_connected(10.0))
        for i in range(5):
            store.create(_obj(f"drip-{i}"))
        pers.flush()
        self.assertTrue(_wait(lambda: len(replica.store) == 5, timeout=10))
        self.assertEqual(follower.frames_rejected, 0)
        self.assertEqual(
            replica.state(),
            canonical_state(store.all_objects(), store._rv),
        )

    def test_s2c_blackhole_detected_by_follower_heartbeat(self):
        """Leader→follower direction goes dark mid-stream. The follower
        hears silence for the timeout, declares the link half-open,
        reconnects — and once healed, the re-bootstrap converges."""
        store, pers, server = self._leader()
        inj = self._injector()
        proxy = inj.proxy("ship", "127.0.0.1", server.port, framed=True)
        replica, follower = self._follower_via(proxy)
        self.assertTrue(follower.wait_connected(5.0))
        store.create(_obj("before"))
        pers.flush()
        self.assertTrue(_wait(lambda: len(replica.store) == 1))

        inj.partition("ship", direction="s2c")
        store.create(_obj("dark-window"))
        pers.flush()
        t0 = time.monotonic()
        self.assertTrue(_wait(
            lambda: follower.heartbeat_timeouts >= 1, timeout=10))
        detect_s = time.monotonic() - t0
        # Bounded detection: timeout + one poll of slack, not "minutes".
        self.assertLess(detect_s, self.HB_TIMEOUT * 3 + 1.0)

        inj.heal("ship")
        self.assertTrue(_wait(lambda: len(replica.store) == 2, timeout=15))
        self.assertEqual(
            replica.state(),
            canonical_state(store.all_objects(), store._rv),
        )

    def test_c2s_blackhole_detected_by_leader_heartbeat(self):
        """Follower→leader direction dark: PONGs are eaten, so the
        LEADER's timeout fires and drops the conn; the follower sees the
        EOF, redials, and heals."""
        from cron_operator_tpu.runtime.manager import Metrics
        metrics = Metrics()
        store, pers, server = self._leader()
        server._metrics = metrics
        inj = self._injector()
        proxy = inj.proxy("ship", "127.0.0.1", server.port, framed=True)
        replica, follower = self._follower_via(proxy)
        self.assertTrue(follower.wait_connected(5.0))

        inj.partition("ship", direction="c2s")
        self.assertTrue(_wait(
            lambda: metrics.counters.get(
                'transport_heartbeat_timeouts_total{side="leader"}', 0) >= 1,
            timeout=10,
        ))
        inj.heal("ship")
        store.create(_obj("after-heal"))
        pers.flush()
        self.assertTrue(_wait(lambda: len(replica.store) == 1, timeout=15))

    def test_counterproof_no_heartbeats_wedges_silently(self):
        """The same s2c blackhole with heartbeats OFF: the follower
        blocks in recv forever — no timeout, no reconnect, follower lag
        growing silently. This is the failure mode the tentpole exists
        to close; the chaos soak's --expect-violation leg automates it."""
        store, pers, server = self._leader(heartbeats=False)
        inj = self._injector()
        proxy = inj.proxy("ship", "127.0.0.1", server.port, framed=True)
        replica, follower = self._follower_via(proxy, heartbeats=False)
        self.assertTrue(follower.wait_connected(5.0))
        store.create(_obj("seen"))
        pers.flush()
        self.assertTrue(_wait(lambda: len(replica.store) == 1))

        inj.partition("ship", direction="s2c")
        for i in range(3):
            store.create(_obj(f"unseen-{i}"))
        pers.flush()
        # Give it several heartbeat-timeouts' worth of wall time: with
        # detection disabled, NOTHING happens.
        time.sleep(self.HB_TIMEOUT * 2.5)
        self.assertEqual(follower.reconnects, 0)
        self.assertEqual(follower.heartbeat_timeouts, 0)
        self.assertEqual(len(replica.store), 1)  # lag, growing silently


class TestRetryBudget(unittest.TestCase):
    def test_first_tries_never_gated_retries_spend(self):
        b = RetryBudget(max_tokens=10.0, token_ratio=0.1)
        self.assertFalse(b.depleted)
        # 5 retries take the bucket from 10 to 5 == half: grants stop.
        for _ in range(5):
            self.assertTrue(b.try_retry())
        self.assertTrue(b.depleted)
        self.assertFalse(b.try_retry())
        self.assertEqual(b.stats()["granted"], 5)
        self.assertEqual(b.stats()["denied"], 1)

    def test_successes_refund_toward_cap(self):
        b = RetryBudget(max_tokens=10.0, token_ratio=0.5)
        for _ in range(5):
            b.try_retry()
        self.assertTrue(b.depleted)
        # Each success refunds token_ratio; 2 successes puts the bucket
        # above half again.
        b.on_success()
        b.on_success()
        self.assertFalse(b.depleted)
        self.assertTrue(b.try_retry())
        # Refunds never overflow the cap.
        for _ in range(1000):
            b.on_success()
        self.assertEqual(b.stats()["tokens"], 10.0)

    def test_exhaustion_counts_into_metrics(self):
        from cron_operator_tpu.runtime.manager import Metrics
        metrics = Metrics()
        b = RetryBudget(max_tokens=2.0, token_ratio=0.1, metrics=metrics)
        # First spend: 2.0 > 1.0 → granted (tokens now 1.0). Second:
        # 1.0 > 1.0 is false → denied and counted.
        self.assertTrue(b.try_retry())
        self.assertFalse(b.try_retry())
        self.assertGreaterEqual(
            metrics.counters.get("router_retry_budget_exhausted_total", 0), 1)


if __name__ == "__main__":
    unittest.main()
