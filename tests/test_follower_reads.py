"""Follower read plane (runtime/readroute.py + transport role runners).

The read-path scaling seams:

- rv barriers under injected follower lag: a barriered read against a
  stalled replica BLOCKS, resumes exactly when the replayed rv catches
  up, and a barrier that times out surfaces as HTTP 504
  ``FollowerBehind`` — which the router's read plane converts into a
  counted leader fallback (``reason="lag"``);
- read-your-writes through the router: a write proxied by the router
  stamps its committed rv onto every subsequent follower read, so
  write-then-list through the front door can never observe the
  pre-write state, without the client sending any rv itself;
- ``consistency=strong`` pins reads to the leader (the escape hatch in
  the documented consistency model);
- a mid-stream ship re-bootstrap (socket reconnect) re-syncs attached
  watch streams via the per-kind 410 → re-list machinery — no silently
  dropped events — and surfaces as a typed
  ``cluster_events_total{event="follower_resync"}`` event;
- a follower-served watch stream delivers the same event set as the
  leader's across a ``kill -9`` promotion (the standby's attached read
  door stays up while its replica store becomes the new leader store);
- teardown ordering: router stops before the follower door, door
  before the leader serving — no ERROR logs (the PR 13 de-flake shape).
"""

import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import threading
import time
import unittest
import urllib.request

from cron_operator_tpu.api.scheme import GVK_JAXJOB
from cron_operator_tpu.runtime.kube import (
    APIServer,
    FollowerBehindError,
    InvalidError,
)
from cron_operator_tpu.runtime.manager import Metrics
from cron_operator_tpu.runtime.readroute import (
    READ_CONSISTENCY,
    FollowerReadAPI,
    FollowerReadClient,
)
from cron_operator_tpu.runtime.shard import FollowerReplica
from cron_operator_tpu.runtime.transport import (
    FollowerReadServer,
    RouterServer,
    ShardClient,
    ShardServing,
    WALShipServer,
)
from cron_operator_tpu.runtime.persistence import Persistence
from cron_operator_tpu.utils.clock import FakeClock, RealClock

WORKLOAD_API_VERSION = "kubeflow.org/v1"
WORKLOAD_KIND = "JAXJob"


def _obj(name: str, ns: str = "default", labels=None) -> dict:
    meta = {"name": name, "namespace": ns}
    if labels:
        meta["labels"] = dict(labels)
    return {
        "apiVersion": WORKLOAD_API_VERSION,
        "kind": WORKLOAD_KIND,
        "metadata": meta,
        "spec": {"replicaSpecs": {"Worker": {"replicas": 1}}},
    }


def _wait(predicate, timeout=5.0, interval=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return predicate()


def _feed(replica: FollowerReplica, rv: int, name: str) -> None:
    """Apply one WAL put record to a manually-fed replica (the unit
    analog of one shipped flush). The object carries the leader-assigned
    resourceVersion — replicate_put mints nothing."""
    obj = _obj(name)
    obj["metadata"]["resourceVersion"] = rv
    replica.apply_bytes(
        json.dumps({"op": "put", "rv": rv, "obj": obj}).encode() + b"\n"
    )


class TestRvBarrier(unittest.TestCase):
    """wait_min_rv over a real front door: block, resume at rv, 504."""

    def setUp(self):
        self.replica = FollowerReplica(RealClock(), name="barrier-test")
        self.metrics = Metrics()
        self.read_api = FollowerReadAPI(
            self.replica, metrics=self.metrics, barrier_timeout_s=0.25
        )
        from cron_operator_tpu.runtime.apiserver_http import HTTPAPIServer
        self.http = HTTPAPIServer(
            api=self.read_api, durable_writes=False, read_source="follower"
        )
        self.http.start()
        self.addCleanup(self.http.stop)
        self.client = ShardClient(f"http://127.0.0.1:{self.http.port}")
        self.addCleanup(self.client.close)

    def test_satisfied_barrier_is_fast_path(self):
        _feed(self.replica, 1, "w-0")
        items, rv = self.client.list_with_rv(
            WORKLOAD_API_VERSION, WORKLOAD_KIND, min_rv=1
        )
        self.assertEqual(len(items), 1)
        self.assertGreaterEqual(int(rv), 1)
        # Fast path: the barrier never blocked, but the wait histogram
        # still saw a (zero) sample — lag stays observable at p50 too.
        self.assertEqual(self.read_api.barrier_waits, 0)
        self.assertGreaterEqual(self.metrics._hists[
            "follower_read_barrier_wait_seconds"]["count"], 1)

    def test_blocked_read_resumes_exactly_at_rv(self):
        _feed(self.replica, 1, "w-0")
        got = {}

        def barriered_read():
            got["result"] = self.client.list_with_rv(
                WORKLOAD_API_VERSION, WORKLOAD_KIND, min_rv=3
            )

        t = threading.Thread(target=barriered_read)
        t.start()
        # The read is parked on the barrier while the replica lags.
        time.sleep(0.08)
        self.assertTrue(t.is_alive())
        self.assertEqual(self.read_api.barrier_waits, 1)
        _feed(self.replica, 2, "w-1")
        _feed(self.replica, 3, "w-2")
        t.join(timeout=5)
        self.assertFalse(t.is_alive())
        items, rv = got["result"]
        # Resumed exactly at the barrier rv: all three writes visible.
        self.assertEqual(
            sorted(i["metadata"]["name"] for i in items),
            ["w-0", "w-1", "w-2"],
        )
        self.assertGreaterEqual(int(rv), 3)
        self.assertEqual(self.read_api.barrier_timeouts, 0)

    def test_barrier_timeout_maps_to_follower_behind(self):
        _feed(self.replica, 1, "w-0")
        t0 = time.monotonic()
        with self.assertRaises(FollowerBehindError):
            self.client.list_with_rv(
                WORKLOAD_API_VERSION, WORKLOAD_KIND, min_rv=99
            )
        # Bounded wait: the 504 came at the configured timeout, not the
        # client's socket timeout.
        self.assertLess(time.monotonic() - t0, 2.0)
        self.assertEqual(self.read_api.barrier_timeouts, 1)

    def test_write_verbs_refused(self):
        with self.assertRaises(InvalidError):
            self.client.create(_obj("nope"))
        self.assertEqual(len(self.replica.store), 0)


class TestRouterReadPlane(unittest.TestCase):
    """FollowerReadClient: round-robin, barriers, fallbacks, strong."""

    def setUp(self):
        self.metrics = Metrics()
        self.store = APIServer(clock=FakeClock())
        self.addCleanup(self.store.close)
        from cron_operator_tpu.runtime.apiserver_http import HTTPAPIServer
        self.leader_http = HTTPAPIServer(
            api=self.store, durable_writes=False, read_source="leader"
        )
        self.leader_http.start()
        self.addCleanup(self.leader_http.stop)
        # A follower whose ship stream is STALLED: nothing ever feeds
        # the replica, so every barriered read times out.
        self.replica = FollowerReplica(RealClock(), name="stalled")
        self.read_api = FollowerReadAPI(self.replica,
                                        barrier_timeout_s=0.15)
        self.follower_http = HTTPAPIServer(
            api=self.read_api, durable_writes=False, read_source="follower"
        )
        self.follower_http.start()
        self.addCleanup(self.follower_http.stop)

        leader = ShardClient(f"http://127.0.0.1:{self.leader_http.port}")
        follower = ShardClient(
            f"http://127.0.0.1:{self.follower_http.port}")
        self.client = FollowerReadClient(
            leader, [follower], metrics=self.metrics
        )
        self.addCleanup(self.client.stop)

    def test_lagging_follower_falls_back_to_leader(self):
        out = self.client.create(_obj("w-0"))
        self.assertGreaterEqual(self.client.last_write_rv, 1)
        self.assertEqual(
            int(out["metadata"]["resourceVersion"]),
            self.client.last_write_rv,
        )
        items = self.client.list(WORKLOAD_API_VERSION, WORKLOAD_KIND)
        # Read-your-writes held — served by the LEADER because the
        # stalled follower blew its barrier.
        self.assertEqual([i["metadata"]["name"] for i in items], ["w-0"])
        stats = self.client.read_stats()
        self.assertEqual(stats["fallbacks"]["lag"], 1)
        self.assertEqual(stats["reads_leader"], 1)
        self.assertEqual(stats["reads_follower"], 0)
        self.assertEqual(self.metrics.counters.get(
            'follower_read_fallbacks_total{reason="lag"}'), 1)
        self.assertEqual(self.metrics.counters.get(
            'http_reads_served_total{source="leader"}'), 1)

    def test_caught_up_follower_serves_the_read(self):
        out = self.client.create(_obj("w-0"))
        rv = int(out["metadata"]["resourceVersion"])
        _feed(self.replica, rv, "w-0")
        items = self.client.list(WORKLOAD_API_VERSION, WORKLOAD_KIND)
        self.assertEqual([i["metadata"]["name"] for i in items], ["w-0"])
        stats = self.client.read_stats()
        self.assertEqual(stats["reads_follower"], 1)
        self.assertEqual(stats["fallbacks"]["lag"], 0)
        self.assertEqual(self.metrics.counters.get(
            'http_reads_served_total{source="follower"}'), 1)

    def test_strong_consistency_pins_the_leader(self):
        self.client.create(_obj("w-0"))
        token = READ_CONSISTENCY.set("strong")
        try:
            self.client.list(WORKLOAD_API_VERSION, WORKLOAD_KIND)
        finally:
            READ_CONSISTENCY.reset(token)
        stats = self.client.read_stats()
        # Never even dialed the follower: no fallback, a leader read.
        self.assertEqual(stats["reads_leader"], 1)
        self.assertEqual(stats["fallbacks"]["lag"], 0)

    def test_dead_follower_counts_unhealthy(self):
        self.client.create(_obj("w-0"))
        self.follower_http.stop()
        items = self.client.list(WORKLOAD_API_VERSION, WORKLOAD_KIND)
        self.assertEqual(len(items), 1)
        stats = self.client.read_stats()
        self.assertEqual(stats["fallbacks"]["unhealthy"], 1)
        self.assertEqual(self.metrics.counters.get(
            'follower_read_fallbacks_total{reason="unhealthy"}'), 1)

    def test_deletes_barrier_follower_reads_too(self):
        self.client.create(_obj("w-0"))
        rv_before = self.client.last_write_rv
        self.client.delete(WORKLOAD_API_VERSION, WORKLOAD_KIND,
                           "default", "w-0")
        # The delete's Status carried the post-delete collection rv —
        # a follower still showing the object can never satisfy it.
        self.assertGreater(self.client.last_write_rv, rv_before)


class TestReadYourWritesThroughRouter(unittest.TestCase):
    """End-to-end: real shard leader + real ship-fed follower door +
    router with read_peers; write-then-list through the router's own
    front door never observes the pre-write state."""

    def setUp(self):
        self.dir = tempfile.mkdtemp(prefix="follower-reads-")
        self.addCleanup(shutil.rmtree, self.dir, ignore_errors=True)
        self.metrics = Metrics()
        self.serving = ShardServing(0, data_dir=self.dir,
                                    metrics=self.metrics)
        self.door = FollowerReadServer(
            0, ship_port=self.serving.ship_port, metrics=self.metrics
        )
        self.assertTrue(self.door.follower.wait_connected(5.0))
        self.router = RouterServer(
            peers=[f"127.0.0.1:{self.serving.api_port}"],
            read_peers=[[f"127.0.0.1:{self.door.port}"]],
            metrics=self.metrics,
        )
        # Teardown mirrors the de-flake ordering: router (client
        # streams) first, then the follower door, then the leader.
        self.addCleanup(self.serving.close)
        self.addCleanup(self.door.close)
        self.addCleanup(self.router.close)
        self.front = ShardClient(f"http://127.0.0.1:{self.router.port}")
        self.addCleanup(self.front.close)

    def test_write_then_list_is_never_stale(self):
        stale = 0
        for i in range(30):
            name = f"ryw-{i}"
            self.front.create(_obj(name, labels={"pair": str(i)}))
            items = self.front.list(
                WORKLOAD_API_VERSION, WORKLOAD_KIND,
                label_selector={"pair": str(i)},
            )
            if [x["metadata"]["name"] for x in items] != [name]:
                stale += 1
        self.assertEqual(stale, 0)
        stats = self.router.clients[0].read_stats()
        # The reads were actually follower-served, not leader reads
        # that would hold RYW trivially.
        self.assertGreaterEqual(stats["reads_follower"], 25)
        self.assertEqual(stats["last_write_rv"], 30)

    def test_explicit_min_rv_and_strong_params(self):
        out = self.front.create(_obj("explicit-0"))
        rv = int(out["metadata"]["resourceVersion"])
        items, got_rv = self.front.list_with_rv(
            WORKLOAD_API_VERSION, WORKLOAD_KIND, min_rv=rv
        )
        self.assertEqual(len(items), 1)
        self.assertGreaterEqual(int(got_rv), rv)
        before = self.router.clients[0].read_stats()["reads_leader"]
        items, _ = self.front.list_with_rv(
            WORKLOAD_API_VERSION, WORKLOAD_KIND, consistency="strong"
        )
        self.assertEqual(len(items), 1)
        self.assertEqual(
            self.router.clients[0].read_stats()["reads_leader"],
            before + 1,
        )

    def test_debug_shards_carries_read_plane(self):
        self.front.create(_obj("dbg-0"))
        self.front.list(WORKLOAD_API_VERSION, WORKLOAD_KIND)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{self.router.port}/debug/shards",
                timeout=2.0) as r:
            doc = json.loads(r.read())
        roles = {}
        for entry in doc["shards"]:
            roles.setdefault(entry.get("role", "leader"), []).append(entry)
        # The leader entry carries the router-side read-plane stats;
        # the follower door fans in its own freshness self-report.
        leader = [e for e in doc["shards"] if "read_plane" in e]
        self.assertTrue(leader)
        self.assertGreaterEqual(
            leader[0]["read_plane"]["reads_follower"], 1)
        followers = roles.get("follower") or []
        self.assertTrue(followers)
        reads = followers[0]["reads"]
        for key in ("rv", "staleness_s", "read_qps", "reads_served",
                    "barrier_waits"):
            self.assertIn(key, reads)


class TestFollowerResyncEvent(unittest.TestCase):
    """A mid-stream ship reconnect re-bootstraps the replica: attached
    watch streams re-sync through 410 → re-list (no dropped events) and
    the resync lands as a typed cluster event — while the FIRST
    bootstrap (normal startup) emits nothing."""

    def setUp(self):
        self.metrics = Metrics()
        self.store = APIServer(clock=FakeClock())
        self.addCleanup(self.store.close)
        self.dir = tempfile.mkdtemp(prefix="resync-evt-")
        self.addCleanup(shutil.rmtree, self.dir, ignore_errors=True)
        self.pers = Persistence(self.dir, fsync_every=1)
        self.pers.start(self.store)
        self.addCleanup(self.pers.close)
        self.ship = WALShipServer(self.pers)
        self.addCleanup(self.ship.close)
        self.door = FollowerReadServer(
            0, ship_port=self.ship.port, metrics=self.metrics
        )
        self.addCleanup(self.door.close)
        self.assertTrue(self.door.follower.wait_connected(5.0))

    def _events(self):
        doc = json.loads(self.door.debug_events())
        return [r["event"] for r in doc["records"]]

    def test_rebootstrap_emits_event_and_resyncs_streams(self):
        # Startup bootstrap: replica synced, NO resync event.
        self.store.create(_obj("pre-0"))
        self.pers.flush()
        self.assertTrue(
            _wait(lambda: len(self.door.replica.store) == 1))
        self.assertNotIn("follower_resync", self._events())
        self.assertIsNone(self.metrics.counters.get(
            'cluster_events_total{event="follower_resync"}'))

        # A live watch stream on the door, then a severed ship socket
        # with writes landing during the dark window.
        seen = []
        watcher = ShardClient(f"http://127.0.0.1:{self.door.port}")
        self.addCleanup(watcher.close)
        watcher.add_watcher(lambda evt: seen.append(
            (evt.type, evt.object["metadata"]["name"])))
        watcher.start_watches(gvks=[GVK_JAXJOB])
        self.assertTrue(_wait(
            lambda: ("ADDED", "pre-0") in seen, timeout=10))

        for conn in list(self.ship._conns):
            conn.close()
        self.store.create(_obj("dark-0"))
        self.pers.flush()

        # Reconnect → re-bootstrap → typed event (exactly the resyncs
        # past the first), and the dark-window write reaches the
        # stream via the 410 → re-list path.
        self.assertTrue(_wait(
            lambda: self.door.follower.bootstraps >= 2, timeout=10))
        self.assertTrue(_wait(
            lambda: "follower_resync" in self._events(), timeout=5))
        self.assertGreaterEqual(self.metrics.counters.get(
            'cluster_events_total{event="follower_resync"}', 0), 1)
        self.assertTrue(_wait(
            lambda: ("ADDED", "dark-0") in seen, timeout=10))


class TestWatchAcrossPromotion(unittest.TestCase):
    """A follower-served watch stream delivers every event across a
    ``kill -9`` leader death: the standby's attached read door stays
    up through promotion (its replica store becomes the leader store),
    so watchers riding the door see the full sequence."""

    def setUp(self):
        self.dir = tempfile.mkdtemp(prefix="promo-watch-")
        self.addCleanup(shutil.rmtree, self.dir, ignore_errors=True)

    def test_stream_survives_kill9_promotion(self):
        api, ship, door = 26150, 26151, 26152
        logd = os.path.join(self.dir, "logs")
        os.makedirs(logd)

        def spawn(role_args, tag):
            log = open(os.path.join(logd, f"{tag}.log"), "ab")
            return subprocess.Popen(
                [sys.executable, "-m", "cron_operator_tpu.cli.main",
                 "start", "--health-probe-bind-address", "0",
                 "--lease-ttl", "0.5"] + role_args,
                stdout=log, stderr=subprocess.STDOUT)

        def shard_doc(port):
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/debug/shards",
                        timeout=1.0) as r:
                    return (json.loads(r.read()).get("shards")
                            or [None])[0]
            except Exception:
                return None

        procs = []
        try:
            leader = spawn([
                "--shard-role", "shard", "--shard-index", "0",
                "--data-dir", self.dir,
                "--serve-api", f"127.0.0.1:{api}",
                "--ship-port", str(ship)], "leader")
            procs.append(leader)
            self.assertTrue(_wait(lambda: shard_doc(api), timeout=30))
            leader_pid = shard_doc(api)["pid"]

            standby = spawn([
                "--shard-role", "standby", "--shard-index", "0",
                "--data-dir", self.dir,
                "--serve-api", f"127.0.0.1:{api}",
                "--ship-port", str(ship),
                "--serve-reads", str(door)], "standby")
            procs.append(standby)
            self.assertTrue(_wait(lambda: shard_doc(door), timeout=30))

            seen = []
            watcher = ShardClient(f"http://127.0.0.1:{door}")
            self.addCleanup(watcher.close)
            watcher.add_watcher(lambda evt: seen.append(
                (evt.type, evt.object["metadata"]["name"])))
            watcher.start_watches(gvks=[GVK_JAXJOB])

            writer = ShardClient(f"http://127.0.0.1:{api}")
            pre = [f"pre-{i}" for i in range(5)]
            for name in pre:
                writer.create(_obj(name))
            writer.close()
            self.assertTrue(_wait(
                lambda: all(("ADDED", n) in seen for n in pre),
                timeout=15))

            os.kill(leader_pid, signal.SIGKILL)
            # Promotion rebinds the SAME api port (a SIGKILLed leader
            # frees it), so the new leader shows a different pid there.
            self.assertTrue(_wait(
                lambda: (shard_doc(api) or {}).get("pid")
                not in (None, leader_pid),
                timeout=30))

            post = [f"post-{i}" for i in range(5)]
            writer = ShardClient(f"http://127.0.0.1:{api}")
            for name in post:
                writer.create(_obj(name))
            # The follower-served stream delivers the full sequence —
            # pre-kill AND post-promotion — matching the leader's view.
            self.assertTrue(_wait(
                lambda: all(("ADDED", n) in seen for n in pre + post),
                timeout=30))
            leader_names = sorted(
                i["metadata"]["name"] for i in writer.list(
                    WORKLOAD_API_VERSION, WORKLOAD_KIND))
            writer.close()
            door_names = sorted(
                i["metadata"]["name"] for i in ShardClient(
                    f"http://127.0.0.1:{door}").list(
                        WORKLOAD_API_VERSION, WORKLOAD_KIND))
            self.assertEqual(door_names, leader_names)
            self.assertEqual(door_names, sorted(pre + post))
        finally:
            for p in procs:
                if p.poll() is None:
                    p.terminate()
            for p in procs:
                try:
                    p.wait(timeout=15)
                except subprocess.TimeoutExpired:
                    p.kill()


class TestTeardownOrdering(unittest.TestCase):
    """De-flake: RouterServer.stop() before the follower front door —
    follower-served streams end cleanly, no ERROR tracebacks."""

    def test_router_stops_before_follower_door_cleanly(self):
        d = tempfile.mkdtemp(prefix="teardown-")
        self.addCleanup(shutil.rmtree, d, ignore_errors=True)
        serving = ShardServing(0, data_dir=d)
        door = FollowerReadServer(0, ship_port=serving.ship_port)
        self.assertTrue(door.follower.wait_connected(5.0))
        router = RouterServer(
            peers=[f"127.0.0.1:{serving.api_port}"],
            read_peers=[[f"127.0.0.1:{door.port}"]],
        )
        front = ShardClient(f"http://127.0.0.1:{router.port}")
        front.create(_obj("t-0"))
        self.assertEqual(len(front.list(
            WORKLOAD_API_VERSION, WORKLOAD_KIND)), 1)
        front.close()
        with self.assertNoLogs(level="ERROR"):
            router.close()   # read-plane watch streams stop first
            door.close()     # then the follower front door
            serving.close()  # leader last
            time.sleep(0.2)


if __name__ == "__main__":
    unittest.main()
