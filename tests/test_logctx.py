"""Per-request log context (utils/logctx.py): structured ``key=value``
fields ahead of the message, in fixed order — controller, namespaced
name, trace id, extras — so log lines correlate with the prometheus
``controller`` label and ``/debug/traces`` span trace ids."""

from __future__ import annotations

from cron_operator_tpu.utils.logctx import request_logger


def _render(log, msg="hello"):
    rendered, _ = log.process(msg, {})
    return rendered


class TestRequestLogger:
    def test_controller_and_namespaced_name(self):
        log = request_logger("cron", namespace="default", name="demo")
        assert _render(log) == "[controller=cron cron=default/demo] hello"

    def test_trace_field_renders_after_name(self):
        log = request_logger(
            "cron", namespace="default", name="demo", trace="cafe0123"
        )
        assert _render(log) == (
            "[controller=cron cron=default/demo trace=cafe0123] hello"
        )

    def test_extra_fields_follow_trace(self):
        log = request_logger(
            "cron", namespace="ns", name="x", trace="ab12", job="ns/j-1"
        )
        assert _render(log) == (
            "[controller=cron cron=ns/x trace=ab12 job=ns/j-1] hello"
        )

    def test_field_order_is_fixed_regardless_of_kwargs(self):
        # trace is a named parameter, not an **fields entry — it always
        # lands between the namespaced name and the extras.
        log = request_logger("cron", name="x", job="j", trace="t1")
        assert _render(log) == "[controller=cron cron=x trace=t1 job=j] hello"

    def test_no_trace_no_field(self):
        log = request_logger("cron", namespace="ns", name="x")
        assert "trace=" not in _render(log)

    def test_controller_lowercased_and_cluster_scoped_name(self):
        log = request_logger("Cron", name="x")
        assert _render(log) == "[controller=cron cron=x] hello"

    def test_logger_name_is_controller_scoped(self):
        log = request_logger("cron", namespace="ns", name="x", trace="t")
        assert log.logger.name == "controller.cron"
