"""Checkpoint/resume tests: Orbax-backed TrainState persistence, lineage
naming, and the full preemption→restart→resume loop through the executor
(BASELINE.md acceptance config 5's recovery half)."""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cron_operator_tpu.backends.local import LocalExecutor
from cron_operator_tpu.models import MLP
from cron_operator_tpu.parallel.mesh import mesh_for_devices
from cron_operator_tpu.runtime.kube import APIServer
from cron_operator_tpu.utils.clock import RealClock
from cron_operator_tpu.workloads import data as datasets
from cron_operator_tpu.workloads.checkpoint import CheckpointStore, job_family
from cron_operator_tpu.workloads.train import TrainConfig, Trainer


def test_job_family_strips_tick_suffix():
    assert job_family("bert-1785339801") == "bert"
    assert job_family("my-cron-name-1785339801") == "my-cron-name"
    # non-tick numeric suffixes stay (too short to be a unix timestamp)
    assert job_family("resnet-50") == "resnet-50"
    assert job_family("plain") == "plain"


@pytest.fixture
def cpus():
    return jax.devices("cpu")


def _trainer(cpus, store, save_every=1):
    mesh = mesh_for_devices(cpus)
    with jax.default_device(cpus[0]):
        m = MLP(features=(32,))
        params = m.init(jax.random.PRNGKey(0), jnp.zeros((1, 28, 28, 1)))[
            "params"
        ]
        return Trainer(
            lambda p, x: m.apply({"params": p}, x), params, mesh,
            TrainConfig(optimizer="sgd", save_every=save_every),
            checkpoint=store,
        )


class TestTrainerResume:
    def test_restore_continues_from_saved_step(self, cpus, tmp_path):
        # Cross-tick resume is opt-in (lineage="family"): the default
        # per-job lineage keeps concurrent Allow/Replace ticks isolated.
        it = datasets.mnist_batches(16, seed=9)
        t1 = _trainer(cpus, CheckpointStore("ns", "job-1785339000",
                                            root=str(tmp_path),
                                            lineage="family"))
        t1.run(it, steps=3)
        assert t1.steps_done == 3
        t1.checkpoint.close()

        # Same cron family, next tick: restores step 3 and runs only 4-5.
        t2 = _trainer(cpus, CheckpointStore("ns", "job-1785339060",
                                            root=str(tmp_path),
                                            lineage="family"))
        assert t2.steps_done == 3
        np.testing.assert_allclose(
            np.asarray(t1.state.params["Dense_0"]["kernel"]),
            np.asarray(t2.state.params["Dense_0"]["kernel"]),
        )
        stats = t2.run(datasets.mnist_batches(16, seed=9), steps=5)
        assert [s.step for s in stats] == [4, 5]
        t2.checkpoint.close()

    def test_target_reached_runs_nothing(self, cpus, tmp_path):
        store = CheckpointStore("ns", "done-1785339000", root=str(tmp_path),
                                lineage="family")
        t1 = _trainer(cpus, store)
        t1.run(datasets.mnist_batches(16), steps=2)
        t1.checkpoint.close()
        t2 = _trainer(cpus, CheckpointStore("ns", "done-1785339099",
                                            root=str(tmp_path),
                                            lineage="family"))
        stats = t2.run(datasets.mnist_batches(16), steps=2)
        assert stats == [] and t2.steps_done == 2
        t2.checkpoint.close()


    def test_default_lineage_isolates_ticks(self, cpus, tmp_path):
        # Default (per-job) lineage: a later tick must NOT see an earlier
        # tick's checkpoints — Allow/Replace concurrency safety.
        t1 = _trainer(cpus, CheckpointStore("ns", "iso-1785339000",
                                            root=str(tmp_path)))
        t1.run(datasets.mnist_batches(16), steps=2)
        t1.checkpoint.close()
        t2 = _trainer(cpus, CheckpointStore("ns", "iso-1785339060",
                                            root=str(tmp_path)))
        assert t2.steps_done == 0
        t2.checkpoint.close()


class TestRestoreFallbackChain:
    """Integrity fallback: a torn async save (preemption mid-write, disk
    fault under the checkpoint root) leaves the NEWEST retained step
    unreadable — resume must walk back to the previous retained step
    instead of crashing the restarted job."""

    def _saved_store(self, tmp_path, steps=(1, 2, 3)):
        import jax.numpy as jnp

        store = CheckpointStore("ns", "torn", root=str(tmp_path))
        state = {"params": {"w": jnp.arange(8.0)}, "step": jnp.int32(0)}
        for s in steps:
            state["step"] = jnp.int32(s)
            store.save(s, state)
        store.wait()
        store.close()
        return tmp_path / "ns" / "torn"

    def _truncate_step(self, lineage_dir, step):
        # Empty every payload file but keep _CHECKPOINT_METADATA, so the
        # step still LISTS as retained (the realistic torn-save shape:
        # the commit marker survives, the data does not).
        for p in (lineage_dir / str(step)).rglob("*"):
            if p.is_file() and p.name != "_CHECKPOINT_METADATA":
                p.write_bytes(b"")

    def test_truncated_latest_falls_back_to_previous_step(self, tmp_path):
        import jax.numpy as jnp
        import numpy as np

        lineage = self._saved_store(tmp_path)
        self._truncate_step(lineage, 3)

        class Sink:
            def __init__(self):
                self.series = {}

            def inc(self, series, value=1):
                self.series[series] = self.series.get(series, 0) + value

        store = CheckpointStore("ns", "torn", root=str(tmp_path))
        sink = Sink()
        store.instrument(sink)
        try:
            # Step 3 still lists — a bare latest_step() restore would die.
            assert store.latest_step() == 3
            like = {"params": {"w": jnp.zeros(8)}, "step": jnp.int32(0)}
            step, out = store.restore_latest(like)
            assert step == 2
            assert int(out["step"]) == 2
            np.testing.assert_allclose(
                np.asarray(out["params"]["w"]), np.arange(8.0)
            )
            assert store.fallbacks == 1
            assert sink.series == {
                "workload_checkpoint_fallbacks_total": 1
            }
        finally:
            store.close()

    def test_all_steps_truncated_raises(self, tmp_path):
        import jax.numpy as jnp

        lineage = self._saved_store(tmp_path, steps=(1, 2))
        self._truncate_step(lineage, 1)
        self._truncate_step(lineage, 2)
        store = CheckpointStore("ns", "torn", root=str(tmp_path))
        try:
            like = {"params": {"w": jnp.zeros(8)}, "step": jnp.int32(0)}
            with pytest.raises(Exception):
                store.restore_latest(like)
            assert store.fallbacks == 2
        finally:
            store.close()

    def test_empty_lineage_raises_file_not_found(self, tmp_path):
        store = CheckpointStore("ns", "fresh", root=str(tmp_path))
        try:
            with pytest.raises(FileNotFoundError, match="no checkpoint"):
                store.restore_latest({"w": 0})
            assert store.fallbacks == 0
        finally:
            store.close()


class TestPreemptionResume:
    """Executor loop: preempt a checkpointing job mid-run; the restarted
    run resumes from the saved step instead of starting over."""

    def test_preempt_then_resume(self, tmp_path):
        api = APIServer(clock=RealClock())
        ex = LocalExecutor(api)
        ex.start()
        job = {
            "apiVersion": "kubeflow.org/v1",
            "kind": "JAXJob",
            "metadata": {
                "name": "mnist-pre", "namespace": "default",
                "annotations": {
                    "tpu.kubedl.io/entrypoint": "mnist",
                    "tpu.kubedl.io/restart-on-preemption": "true",
                    "tpu.kubedl.io/param.steps": "400",
                    "tpu.kubedl.io/param.batch_size": "8",
                    "tpu.kubedl.io/param.platform": "cpu",
                    "tpu.kubedl.io/param.checkpoint": "1",
                    "tpu.kubedl.io/param.save_every": "5",
                    "tpu.kubedl.io/param.checkpoint_dir": str(tmp_path),
                },
            },
            "spec": {"replicaSpecs": {"Worker": {"replicas": 1}}},
        }
        try:
            api.create(job)
            # Wait until some steps are checkpointed.
            deadline = time.time() + 90.0
            progressed = 0
            while time.time() < deadline and progressed < 10:
                j = api.get("kubeflow.org/v1", "JAXJob", "default", "mnist-pre")
                progressed = (
                    (j.get("status") or {})
                    .get("trainingProgress", {})  # published only at end
                    .get("steps_done", 0)
                )
                store = CheckpointStore("default", "mnist-pre",
                                        root=str(tmp_path))
                progressed = store.latest_step() or 0
                time.sleep(0.3)
            assert progressed >= 10, "job never checkpointed progress"

            ex.preempt("default", "mnist-pre")
            # The re-run resumes; wait for resumed_from_step to appear.
            deadline = time.time() + 90.0
            resumed = None
            while time.time() < deadline and resumed is None:
                j = api.get("kubeflow.org/v1", "JAXJob", "default", "mnist-pre")
                prog = (j.get("status") or {}).get("trainingProgress") or {}
                resumed = prog.get("resumed_from_step")
                # stop the long re-run once we've seen the resume marker
                time.sleep(0.3)
            assert resumed is not None and resumed >= 10
        finally:
            # Cancel the (long) re-run and shut down.
            api.delete("kubeflow.org/v1", "JAXJob", "default", "mnist-pre")
            ex.stop()


class TestElasticResume:
    def test_restore_across_different_mesh_topology(self, cpus, tmp_path):
        """Elastic resharding: a checkpoint saved under one sharding plan
        (fsdp=2) restores into a trainer on a DIFFERENT plan (tensor=2) —
        the restore targets the new mesh layout directly (Orbax
        restore-into-`like`), so a rescheduled job can resume on whatever
        slice shape it lands on."""
        from cron_operator_tpu.models import MLP
        from cron_operator_tpu.workloads.train import TrainConfig, Trainer

        def build(mesh_kwargs, store):
            import jax.numpy as jnp

            mesh = mesh_for_devices(cpus, **mesh_kwargs)
            model = MLP()
            params = model.init(
                jax.random.PRNGKey(0), jnp.zeros((1, 28, 28, 1))
            )["params"]
            return Trainer(
                lambda p, x: model.apply({"params": p}, x), params, mesh,
                TrainConfig(optimizer="sgd", learning_rate=0.05,
                            save_every=2),
                checkpoint=store,
            )

        t1 = build(
            dict(fsdp=2),
            CheckpointStore("ns", "elastic-1785339000",
                            root=str(tmp_path), lineage="family"),
        )
        t1.run(datasets.mnist_batches(16, seed=11), steps=2)
        t1.checkpoint.wait()
        saved = np.asarray(
            jax.device_get(t1.state.params["Dense_0"]["kernel"])
        )
        t1.checkpoint.close()

        t2 = build(
            dict(tensor=2),
            CheckpointStore("ns", "elastic-1785339060",
                            root=str(tmp_path), lineage="family"),
        )
        assert t2.steps_done == 2
        np.testing.assert_allclose(
            np.asarray(jax.device_get(
                t2.state.params["Dense_0"]["kernel"]
            )),
            saved,
        )
        # And it keeps training on the new topology.
        stats = t2.run(datasets.mnist_batches(16, seed=11), steps=4)
        assert [s.step for s in stats] == [3, 4]
        t2.checkpoint.close()


class TestTrainThenServe:
    """The nightly pairing: a cron-scheduled training job checkpoints a
    lineage; a cron-scheduled generate job serves the latest params from
    it (params-only restore — the serving job never needs the training
    job's optimizer config)."""

    def test_generate_restores_trained_params(self, cpus, tmp_path,
                                              monkeypatch):
        import numpy as np

        from cron_operator_tpu.backends.registry import (
            JobContext,
            resolve_entrypoint,
        )
        from cron_operator_tpu.workloads import generate as gen_mod
        from cron_operator_tpu.workloads.checkpoint import CheckpointStore

        common_model = {
            "size": "tiny", "seq_len": "16", "platform": "cpu",
        }
        train_ctx = JobContext(
            name="lm-train-1700000000", namespace="default", job={},
            params={
                **common_model, "steps": "3", "batch_size": "8",
                "checkpoint": "1", "save_every": "3",
                "checkpoint_lineage": "family",
                "checkpoint_dir": str(tmp_path),
            },
        )
        resolve_entrypoint("gpt")(train_ctx)
        assert train_ctx.progress["steps_done"] == 3

        # The family lineage dir is the tick-suffix-stripped name.
        store = CheckpointStore("default", "lm-train", root=str(tmp_path))
        trained = store.restore_params()
        store.close()

        # Spy on the serve path's actual weights: the entrypoint must
        # hand generate() the TRAINED params, not a fresh init.
        served = {}
        real_generate = gen_mod.generate

        def spy(cfg, params, prompt, max_new, **kw):
            served["params"] = params
            return real_generate(cfg, params, prompt, max_new, **kw)

        monkeypatch.setattr(gen_mod, "generate", spy)

        serve_ctx = JobContext(
            name="lm-serve", namespace="default", job={},
            params={
                **common_model, "rounds": "1", "batch_size": "2",
                "prompt_len": "4", "max_new": "4",
                "checkpoint_from": "lm-train",
                "checkpoint_dir": str(tmp_path),
            },
        )
        resolve_entrypoint("generate")(serve_ctx)
        assert serve_ctx.progress["restored_from_step"] == 3
        assert serve_ctx.progress["steps_done"] == 1

        for a, b in zip(
            jax.tree_util.tree_leaves(served["params"]),
            jax.tree_util.tree_leaves(trained),
        ):
            assert np.allclose(np.asarray(a), np.asarray(b)), (
                "serve job did not use the trained checkpoint"
            )

    def test_restore_params_missing_lineage_raises(self, tmp_path):
        from cron_operator_tpu.workloads.checkpoint import CheckpointStore

        store = CheckpointStore("default", "ghost", root=str(tmp_path))
        try:
            with pytest.raises(FileNotFoundError, match="no checkpoint"):
                store.restore_params()
        finally:
            store.close()

    def test_serve_with_typoed_lineage_raises_without_littering(
        self, tmp_path
    ):
        """Read-only open: a mistyped checkpoint_from must raise and must
        NOT create an empty lineage dir in the shared root."""
        from cron_operator_tpu.backends.registry import (
            JobContext,
            resolve_entrypoint,
        )

        ctx = JobContext(
            name="serve-typo", namespace="default", job={},
            params={
                "size": "tiny", "seq_len": "16", "platform": "cpu",
                "rounds": "1", "batch_size": "2", "prompt_len": "4",
                "max_new": "4", "checkpoint_from": "gpt-nightly-tarin",
                "checkpoint_dir": str(tmp_path),
            },
        )
        with pytest.raises(FileNotFoundError, match="no checkpoint"):
            resolve_entrypoint("generate")(ctx)
        assert not (tmp_path / "default" / "gpt-nightly-tarin").exists()
