"""Wall-clock acceptance smokes — one per concurrency policy, on the LIVE
stack (RealClock Manager worker pools + LocalExecutor threads + actual
training). The deterministic five-config matrix lives in
``test_acceptance.py`` (FakeClock, no sleeps); this tier keeps the
end-to-end proof that the real threads, timers and executor agree with
it. Assertions here are existence-level (a thing happened), not
count-exact (how many times in a window) — that's what made the old
suite load-sensitive (VERDICT r3 #3).
"""

import time

import pytest

from cron_operator_tpu.api.scheme import GVK_CRON, default_scheme
from cron_operator_tpu.backends.local import LocalExecutor
from cron_operator_tpu.backends.tpu import NODESEL_ACCELERATOR
from cron_operator_tpu.controller import CronReconciler
from cron_operator_tpu.runtime import APIServer, Manager

JAX = "kubeflow.org/v1"


def _cron(name, schedule, workload, policy="Allow", history=100, **spec_extra):
    spec = {
        "schedule": schedule,
        "concurrencyPolicy": policy,
        "historyLimit": history,
        "template": {"workload": workload},
    }
    spec.update(spec_extra)
    return {
        "apiVersion": "apps.kubedl.io/v1alpha1",
        "kind": "Cron",
        "metadata": {"name": name, "namespace": "default"},
        "spec": spec,
    }


def _workload(kind="JAXJob", annotations=None, replicas=1):
    return {
        "apiVersion": JAX,
        "kind": kind,
        "metadata": {"annotations": dict(annotations or {})},
        "spec": {"replicaSpecs": {"Worker": {"replicas": replicas}}},
    }


@pytest.fixture
def stack():
    api = APIServer()
    mgr = Manager(api, max_concurrent_reconciles=10)
    rec = CronReconciler(api, metrics=mgr.metrics)
    mgr.add_controller(
        "cron", rec.reconcile, for_gvk=GVK_CRON,
        owns=default_scheme().workload_kinds(),
    )
    ex = LocalExecutor(api)
    ex.start()
    mgr.start()
    yield api, mgr, ex
    mgr.stop()
    ex.stop()
    api.close()


def _jobs(api, kind="JAXJob"):
    return api.list(JAX, kind, namespace="default")


def _active(api, kind="JAXJob"):
    out = []
    for j in _jobs(api, kind):
        conds = [c["type"] for c in (j.get("status") or {}).get("conditions") or []]
        if "Succeeded" not in conds and "Failed" not in conds:
            out.append(j)
    return out


class TestForbidSmoke:
    """Forbid + real JAX training end-to-end: the cron fires, TPU admission
    injects the slice, the executor trains MNIST to completion, and no
    overlap ever appears."""

    def test_trains_without_overlap(self, stack):
        api, _, ex = stack
        api.create(_cron(
            "jax-mnist", "@every 1s",
            _workload("JAXJob", {
                "tpu.kubedl.io/accelerator": "v5e-1",
                "tpu.kubedl.io/entrypoint": "mnist",
                "tpu.kubedl.io/param.steps": "2",
                "tpu.kubedl.io/param.batch_size": "16",
                "tpu.kubedl.io/param.platform": "cpu",
            }),
            policy="Forbid",
        ))
        deadline = time.time() + 60.0
        done = None
        while time.time() < deadline and done is None:
            assert len(_active(api)) <= 1, "Forbid must never overlap"
            for j in _jobs(api):
                st = j.get("status") or {}
                if (st.get("trainingProgress") or {}).get("steps_done") == 2:
                    done = j
            time.sleep(0.2)
        assert done is not None, "mnist job never finished training"
        sel = (done["spec"]["replicaSpecs"]["Worker"]["template"]["spec"]
               ["nodeSelector"])
        assert sel[NODESEL_ACCELERATOR] == "tpu-v5-lite-podslice"


class TestReplaceSmoke:
    """Replace on a multi-host gang: 4 host pods appear for the active
    generation; generations swap rather than stack."""

    def test_gang_pods_and_swap(self, stack):
        api, _, _ = stack
        api.create(_cron(
            "resnet", "@every 2s",
            _workload("JAXJob", {
                "tpu.kubedl.io/accelerator": "tpu-v5-lite-podslice",
                "tpu.kubedl.io/topology": "4x4",
                "tpu.kubedl.io/simulate-duration": "30s",
            }, replicas=4),
            policy="Replace",
        ))
        # Wait until a gang is up, then assert its shape.
        deadline = time.time() + 20.0
        pods = []
        while time.time() < deadline and len(pods) < 4:
            assert len(_active(api)) <= 1, "Replace must never stack runs"
            pods = api.list("v1", "Pod", namespace="default")
            time.sleep(0.2)
        assert len(pods) == 4, "one gang = 4 host pods"
        gen1 = {j["metadata"]["name"] for j in _jobs(api)}
        # Wait for at least one replacement generation.
        deadline = time.time() + 15.0
        while time.time() < deadline:
            names = {j["metadata"]["name"] for j in _jobs(api)}
            if names and names != gen1:
                break
            time.sleep(0.2)
        names = {j["metadata"]["name"] for j in _jobs(api)}
        assert names != gen1, "Replace never swapped generations"
        assert len(names) == 1, "exactly one generation alive"


class TestAllowSmoke:
    """Allow stacks overlapping runs on the live timer."""

    def test_overlap_happens(self, stack):
        api, _, _ = stack
        api.create(_cron(
            "allow3", "@every 1s",
            _workload("JAXJob", {"tpu.kubedl.io/simulate-duration": "6s"}),
            policy="Allow", history=5,
        ))
        deadline = time.time() + 15.0
        max_active = 0
        while time.time() < deadline and max_active < 2:
            max_active = max(max_active, len(_active(api)))
            time.sleep(0.1)
        assert max_active >= 2, f"expected overlap under Allow, saw {max_active}"


class TestPreemptionSmoke:
    """Slice preemption kills the gang; restart-on-preemption re-runs the
    job (Restarting → Running again) — BASELINE config 5's hard case."""

    def test_preemption_restart(self, stack):
        api, _, ex = stack
        api.create(_cron(
            "bert-pre", "@every 1s",
            _workload("JAXJob", {
                "tpu.kubedl.io/accelerator": "v5e-16",
                "tpu.kubedl.io/simulate-duration": "20s",
                "tpu.kubedl.io/restart-on-preemption": "true",
            }),
            policy="Forbid",
        ))
        deadline = time.time() + 20.0
        job = None
        while time.time() < deadline and job is None:
            running = [
                j for j in _jobs(api)
                if any(c["type"] == "Running"
                       for c in (j.get("status") or {}).get("conditions") or [])
            ]
            job = running[0] if running else None
            time.sleep(0.1)
        assert job is not None
        name = job["metadata"]["name"]
        assert len(api.list("v1", "Pod", namespace="default")) == 4

        ex.preempt("default", name)
        deadline = time.time() + 20.0
        restarted = False
        while time.time() < deadline and not restarted:
            j = api.try_get(JAX, "JAXJob", "default", name)
            conds = [c["type"] for c in (j.get("status") or {}).get("conditions") or []]
            restarted = "Restarting" in conds and conds.count("Running") >= 2
            time.sleep(0.1)
        assert restarted, "preempted job must go Restarting and re-run"
