"""Helm chart rendered-output specs — the helm-unittest analog.

The reference pins its chart with helm-unittest specs
(``charts/cron-operator/tests/deployment_test.yaml``: image, replicas,
pullPolicy, args); these tests pin the same surface for our chart via the
in-repo renderer (``utils/helmtmpl`` — a ``helm template`` subset, so the
chart stays a standard Helm chart while being testable without the helm
binary)."""

from pathlib import Path

import pytest
import yaml

from cron_operator_tpu.utils.helmtmpl import Renderer, load_values

CHART = Path(__file__).resolve().parent.parent / "charts" / "cron-operator-tpu"


def render(overrides=None, release="cron-operator-tpu", namespace="default"):
    values = load_values(CHART, overrides or {})
    return Renderer(CHART, values, release=release,
                    namespace=namespace).render_objects()


def find(objs, kind, name_contains=""):
    out = [o for o in objs if o["kind"] == kind
           and name_contains in o["metadata"]["name"]]
    assert out, f"no {kind} matching {name_contains!r} in {[o['kind'] for o in objs]}"
    return out[0]


def container(deploy):
    return deploy["spec"]["template"]["spec"]["containers"][0]


class TestDefaultRender:
    @pytest.fixture(scope="class")
    def objs(self):
        return render()

    def test_all_documents_are_valid_yaml_objects(self, objs):
        kinds = sorted(o["kind"] for o in objs)
        assert kinds == [
            "ClusterRole", "ClusterRoleBinding", "Deployment", "Service",
            "ServiceAccount",
        ]

    def test_values_to_flags_mapping(self, objs):
        """The production contract (reference deployment.yaml:42-63)."""
        args = container(find(objs, "Deployment"))["args"]
        assert args == [
            "start",
            "--api-server=cluster",
            "--backend=none",
            "--zap-encoder=json",
            "--zap-log-level=info",
            "--leader-elect",
            "--max-concurrent-reconciles=10",
            "--qps=30",
            "--burst=50",
            "--metrics-bind-address=:8080",
            # Explicit false (the CLI defaults secure) — the reference
            # chart makes the same choice (its deployment.yaml:62-63);
            # values.metrics.secure=true opts into HTTPS + the https
            # ServiceMonitor.
            "--metrics-secure=false",
            "--health-probe-bind-address=:8081",
        ]

    def test_image_defaults_to_appversion(self, objs):
        meta = yaml.safe_load((CHART / "Chart.yaml").read_text())
        img = container(find(objs, "Deployment"))["image"]
        assert img == f"cron-operator-tpu:{meta['appVersion']}"

    def test_probes_on_health_port(self, objs):
        c = container(find(objs, "Deployment"))
        assert c["livenessProbe"]["httpGet"]["path"] == "/healthz"
        assert c["readinessProbe"]["httpGet"]["path"] == "/readyz"
        ports = {p["name"]: p["containerPort"] for p in c["ports"]}
        assert ports == {"metrics": 8080, "health": 8081}

    def test_rbac_covers_all_workload_kinds(self, objs):
        role = find(objs, "ClusterRole")
        flat = [r for rule in role["rules"] for r in rule["resources"]]
        for kind in ("jaxjobs", "pytorchjobs", "tfjobs", "mpijobs",
                     "xgboostjobs"):
            assert kind in flat and f"{kind}/status" in flat

    def test_binding_targets_serviceaccount(self, objs):
        binding = find(objs, "ClusterRoleBinding")
        sa = find(objs, "ServiceAccount")
        assert binding["subjects"][0]["name"] == sa["metadata"]["name"]
        assert (binding["roleRef"]["name"]
                == find(objs, "ClusterRole")["metadata"]["name"])

    def test_resources_reference_parity(self, objs):
        res = container(find(objs, "Deployment"))["resources"]
        assert res["requests"] == {"cpu": "100m", "memory": "128Mi"}
        assert res["limits"] == {"cpu": "400m", "memory": "512Mi"}


class TestValueOverrides:
    def test_registry_tag_and_pull_policy(self):
        objs = render({"image": {"registry": "gcr.io/proj", "tag": "v9",
                                 "pullPolicy": "Never"}})
        c = container(find(objs, "Deployment"))
        assert c["image"] == "gcr.io/proj/cron-operator-tpu:v9"
        assert c["imagePullPolicy"] == "Never"

    def test_metrics_disabled(self):
        objs = render({"metrics": {"enable": False}})
        args = container(find(objs, "Deployment"))["args"]
        assert "--metrics-bind-address=0" in args
        assert not [o for o in objs if o["kind"] == "Service"]

    def test_leader_election_disabled(self):
        objs = render({"leaderElection": {"enable": False}})
        assert "--leader-elect" not in container(find(objs, "Deployment"))["args"]

    def test_reconciles_qps_burst(self):
        objs = render({"maxConcurrentReconciles": 4, "qps": 5, "burst": 9})
        args = container(find(objs, "Deployment"))["args"]
        assert {"--max-concurrent-reconciles=4", "--qps=5",
                "--burst=9"} <= set(args)

    def test_servicemonitor_and_networkpolicy_opt_in(self):
        objs = render({"metrics": {"serviceMonitor": {"enable": True}},
                       "networkPolicy": {"enable": True}})
        sm = find(objs, "ServiceMonitor")
        assert sm["spec"]["endpoints"][0]["path"] == "/metrics"
        np = find(objs, "NetworkPolicy")
        assert np["spec"]["ingress"][0]["ports"][0]["port"] == 8080

    def test_rbac_and_sa_opt_out(self):
        objs = render({"rbac": {"create": False},
                       "serviceAccount": {"create": False}})
        kinds = {o["kind"] for o in objs}
        assert "ClusterRole" not in kinds
        assert "ServiceAccount" not in kinds

    def test_node_selector_tolerations_pull_secrets(self):
        objs = render({
            "nodeSelector": {"pool": "ops"},
            "tolerations": [{"key": "dedicated", "operator": "Exists"}],
            "image": {"pullSecrets": [{"name": "regcred"}]},
        })
        spec = find(objs, "Deployment")["spec"]["template"]["spec"]
        assert spec["nodeSelector"] == {"pool": "ops"}
        assert spec["tolerations"][0]["key"] == "dedicated"
        assert spec["imagePullSecrets"] == [{"name": "regcred"}]

    def test_release_and_namespace_propagate(self):
        objs = render(release="prod", namespace="ops")
        d = find(objs, "Deployment")
        assert d["metadata"]["name"] == "prod-cron-operator-tpu"
        assert d["metadata"]["namespace"] == "ops"
        binding = find(objs, "ClusterRoleBinding")
        assert binding["subjects"][0]["namespace"] == "ops"

    def test_ci_values_overlay(self):
        values = load_values(CHART, {}, [CHART / "ci" / "values.yaml"])
        objs = Renderer(CHART, values).render_objects()
        c = container(find(objs, "Deployment"))
        assert c["imagePullPolicy"] == "Never"
        assert c["image"].endswith(":latest")


class TestChartCRDs:
    def test_crd_matches_generated(self):
        """The chart ships the same CRD the generator emits (drift guard,
        same contract as tests/test_deploy.py for deploy/crds)."""
        from cron_operator_tpu.api.crd import crd_manifest

        shipped = yaml.safe_load(
            (CHART / "crds" / "apps.kubedl.io_crons.yaml").read_text()
        )
        assert shipped == crd_manifest()


class TestHostTimezone:
    """useHostTimezone parity with the reference chart: hostPath mount of
    /etc/localtime, rendered only when enabled (the per-Cron
    spec.timezone field is the preferred, mount-free mechanism)."""

    def test_disabled_by_default(self):
        dep = find(render(), "Deployment")
        spec = dep["spec"]["template"]["spec"]
        assert "volumes" not in spec
        assert "volumeMounts" not in spec["containers"][0]

    def test_enabled_mounts_localtime(self):
        dep = find(render({"useHostTimezone": True}), "Deployment")
        spec = dep["spec"]["template"]["spec"]
        assert spec["volumes"][0]["hostPath"]["path"] == "/etc/localtime"
        vm = spec["containers"][0]["volumeMounts"][0]
        assert vm["mountPath"] == "/etc/localtime"
        assert vm["readOnly"] is True


class TestSecureMetricsRender:
    """values.metrics.secure=true — the chart's opt-in to the CLI's
    default-secure /metrics (the reference chart pins secure=false; ours
    additionally renders the HTTPS scrape config when opted in)."""

    def test_secure_flag_and_https_servicemonitor(self):
        objs = render({
            "metrics": {"secure": True,
                        "serviceMonitor": {"enable": True}},
        })
        args = container(find(objs, "Deployment"))["args"]
        # Go-style bool formatting (helmtmpl._fmt): must render exactly
        # what real helm renders, or the helm-validate CI job diverges.
        assert "--metrics-secure=true" in args
        sm = find(objs, "ServiceMonitor")
        ep = sm["spec"]["endpoints"][0]
        assert ep["scheme"] == "https"
        assert ep["tlsConfig"]["insecureSkipVerify"] is True
        assert "serviceaccount/token" in ep["bearerTokenFile"]

    def test_default_stays_plain_http(self):
        objs = render({"metrics": {"serviceMonitor": {"enable": True}}})
        args = container(find(objs, "Deployment"))["args"]
        assert "--metrics-secure=false" in args
        ep = find(objs, "ServiceMonitor")["spec"]["endpoints"][0]
        assert "scheme" not in ep

    def test_secure_true_ships_review_rbac(self):
        """metrics.secure=true wires kube-delegated scrape auth, which
        needs the TokenReview/SubjectAccessReview verbs — without this
        RBAC every scrape fails closed with 401."""
        objs = render({"metrics": {"secure": True}})
        auth = find(objs, "ClusterRole", name_contains="metrics-auth")
        flat = [(r.get("apiGroups"), r.get("resources"), r.get("verbs"))
                for r in auth["rules"]]
        assert (["authentication.k8s.io"], ["tokenreviews"],
                ["create"]) in flat
        assert (["authorization.k8s.io"], ["subjectaccessreviews"],
                ["create"]) in flat
        binding = find(objs, "ClusterRoleBinding",
                       name_contains="metrics-auth")
        sa = find(objs, "ServiceAccount")
        assert binding["subjects"][0]["name"] == sa["metadata"]["name"]
        reader = find(objs, "ClusterRole", name_contains="metrics-reader")
        assert reader["rules"][0]["nonResourceURLs"] == ["/metrics"]

    def test_default_ships_no_review_rbac(self):
        objs = render()
        assert not [o for o in objs
                    if "metrics-auth" in o["metadata"]["name"]]

    def test_secure_rbac_not_gated_on_rbac_create(self):
        """rbac.create=false (pre-existing workload RBAC) must NOT
        silently drop the review RBAC the secure-metrics opt-in needs —
        that combination would 401 every scrape with no install-time
        signal."""
        objs = render({"metrics": {"secure": True},
                       "rbac": {"create": False}})
        find(objs, "ClusterRole", name_contains="metrics-auth")
