"""Multi-process ``jax.distributed`` smoke test (VERDICT r3 #2, r2 #7).

The one communication responsibility SURVEY.md §5 assigns the operator is
rendering the coordinator env for ``jax.distributed.initialize`` — the
analog of the training-operator's ``MASTER_ADDR`` rendering
(/root/reference's workloads get theirs from the external kubeflow
operator). Until now only the env *strings* were asserted
(tests/test_tpu_topology.py); this test executes the contract end to end:

  render_coordinator_env → (kubelet-style downward-API resolution) →
  workloads.runner child processes → jax.distributed.initialize →
  an actual cross-process psum over the global mesh.

Two real OS processes, CPU devices, no TPU needed. The only substitution
is the coordinator *address*: the rendered value is the job's headless-
service pod DNS (``<job>-worker-0.<job>.<ns>.svc``), which exists only
in-cluster, so the test rewrites host:port to 127.0.0.1:<free port> while
keeping every other part of the contract (env names, process count,
replica-index label → process id) exactly as rendered.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import textwrap

from cron_operator_tpu.backends.tpu import (
    LABEL_REPLICA_INDEX,
    render_coordinator_env,
    slice_for,
)
from cron_operator_tpu.workloads.runner import PROGRESS_PREFIX

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# The child entrypoint: resolved by the runner as ``dist_smoke_entry:run``
# (module:function import string — backends/registry.py). It performs one
# explicit psum across processes over the global device mesh and reports
# the distributed topology it actually saw.
ENTRY_SOURCE = textwrap.dedent(
    """
    import numpy as np
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from jax.experimental.shard_map import shard_map


    def run(ctx):
        ctx.progress["process_count"] = jax.process_count()
        ctx.progress["process_index"] = jax.process_index()
        ctx.progress["global_devices"] = jax.device_count()
        ctx.progress["local_devices"] = jax.local_device_count()

        # One real collective: each process contributes (its index + 1);
        # psum over the global mesh must see every process's shard.
        mesh = Mesh(np.array(jax.devices()), ("p",))
        local = np.full(
            (jax.local_device_count(),),
            float(jax.process_index() + 1),
            dtype=np.float32,
        )
        x = jax.make_array_from_process_local_data(
            NamedSharding(mesh, P("p")), local
        )
        total = shard_map(
            lambda v: jax.lax.psum(v, "p"),
            mesh=mesh, in_specs=P("p"), out_specs=P(),
        )(x)
        ctx.progress["psum"] = float(np.asarray(total.addressable_data(0))[0])
    """
)


def _resolve_env_like_kubelet(rendered, replica_index: int):
    """Materialize the rendered env the way the kubelet would: literal
    values pass through; downward-API fieldRefs on the replica-index pod
    label resolve to that pod's label value."""
    out = {}
    label_path = f"metadata.labels['{LABEL_REPLICA_INDEX}']"
    for entry in rendered:
        if "value" in entry:
            out[entry["name"]] = entry["value"]
        else:
            field_path = entry["valueFrom"]["fieldRef"]["fieldPath"]
            assert field_path == label_path, field_path
            out[entry["name"]] = str(replica_index)
    return out


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _parse_done(stdout: str):
    for line in stdout.splitlines():
        if line.startswith(PROGRESS_PREFIX):
            rec = json.loads(line[len(PROGRESS_PREFIX):])
            if rec.get("type") == "done":
                return rec["progress"]
    return None


def _run_two_process_entry(tmp_path, module_name: str, source: str):
    """Write ``source`` as ``<module_name>.py``, render the 2-host
    coordinator env exactly as the TPU backend would, spawn one runner
    child per host with the address rewritten to loopback, and return the
    per-process parsed ``done`` progress records."""
    (tmp_path / f"{module_name}.py").write_text(source)

    spec = slice_for("v4", "2x2x2")  # 8 chips / 4 per host = 2 hosts
    assert spec.hosts == 2
    rendered = render_coordinator_env("smoke", "default", spec)

    port = _free_port()
    procs = []
    for i in range(spec.hosts):
        env = dict(os.environ)
        env.update(_resolve_env_like_kubelet(rendered, replica_index=i))
        # In-cluster the coordinator host is pod DNS behind the headless
        # service; locally both "pods" share this loopback.
        host_port = env["JAX_COORDINATOR_ADDRESS"].rsplit(":", 1)
        assert host_port[0] == "smoke-worker-0.smoke.default.svc"
        env["JAX_COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"
        # One CPU device per process — the forced 8-device test mesh would
        # only blur the cross-process shape being asserted.
        env.pop("XLA_FLAGS", None)
        env["JAX_PLATFORMS"] = "cpu"
        # NB: the env var alone is NOT enough — images that register a
        # tunneled TPU plugin at interpreter startup override it, and the
        # child hangs dialing the tunnel. The runner's ``platform=cpu``
        # param pins jax_platforms via jax.config before first backend
        # init (workloads/runner.py _maybe_pin_platform), which wins.
        env["PYTHONPATH"] = os.pathsep.join(
            [str(tmp_path), REPO_ROOT, env.get("PYTHONPATH", "")]
        ).rstrip(os.pathsep)
        env["TPU_JOB_NAME"] = f"smoke-worker-{i}"
        procs.append(
            subprocess.Popen(
                [
                    sys.executable, "-m",
                    "cron_operator_tpu.workloads.runner",
                    f"{module_name}:run",
                    "platform=cpu",
                ],
                env=env, cwd=REPO_ROOT,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            )
        )

    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=300)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()

    for rc, out, err in outs:
        assert rc == 0, f"runner failed rc={rc}\nstderr:\n{err[-2000:]}"

    records = []
    for rc, out, err in outs:
        progress = _parse_done(out)
        assert progress is not None, f"no done record in: {out[-500:]}"
        records.append(progress)
    return spec, records


def test_two_process_psum(tmp_path):
    spec, records = _run_two_process_entry(
        tmp_path, "dist_smoke_entry", ENTRY_SOURCE
    )
    expected_psum = sum(i + 1 for i in range(spec.hosts))  # 1 + 2
    for i, progress in enumerate(records):
        assert progress["process_count"] == spec.hosts
        assert progress["process_index"] == i
        assert progress["global_devices"] == spec.hosts  # 1 CPU dev each
        assert progress["local_devices"] == 1
        assert progress["psum"] == float(expected_psum)


# VERDICT r4 weak #4 / next #5: the actual TRAINING path (Trainer: GSPMD
# step, gradient psum inserted by XLA, optimizer update, donated state)
# crossing a real process boundary — not just a hand-written psum. Each
# process feeds its own half of the global batch via
# make_array_from_process_local_data; both must see the same loss and
# finish with identical parameters (data-parallel SPMD invariant).
TRAIN_ENTRY_SOURCE = textwrap.dedent(
    """
    import numpy as np
    import jax
    import jax.numpy as jnp

    from cron_operator_tpu.models import MLP
    from cron_operator_tpu.parallel.mesh import mesh_for_devices
    from cron_operator_tpu.workloads.train import TrainConfig, Trainer


    def run(ctx):
        ctx.progress["process_count"] = jax.process_count()
        ctx.progress["process_index"] = jax.process_index()

        mesh = mesh_for_devices(jax.devices())  # 2 devices -> data=2
        model = MLP()
        params = jax.jit(model.init)(
            jax.random.PRNGKey(0), jnp.zeros((1, 28, 28, 1))
        )["params"]
        trainer = Trainer(
            lambda p, x: model.apply({"params": p}, x), params, mesh,
            TrainConfig(optimizer="sgd", learning_rate=0.01),
        )

        # Each process contributes ITS OWN half of the global batch
        # (different seeds -> the step only matches if the gradient
        # really crosses the process boundary).
        rng = np.random.default_rng(42 + jax.process_index())
        local_x = rng.normal(size=(4, 28, 28, 1)).astype(np.float32)
        local_y = rng.integers(0, 10, size=(4,)).astype(np.int32)
        batch = {
            "x": jax.make_array_from_process_local_data(
                trainer.batch_sharding["x"], local_x
            ),
            "y": jax.make_array_from_process_local_data(
                trainer.batch_sharding["y"], local_y
            ),
        }
        for step in range(2):  # two steps: the second consumes the
            stats = trainer.step(batch)  # first's updated state
        ctx.progress["loss"] = stats.loss
        ctx.progress["steps_done"] = stats.step
        checksum = sum(
            float(jnp.sum(jnp.abs(l)))
            for l in jax.tree_util.tree_leaves(trainer.state.params)
        )
        ctx.progress["param_checksum"] = round(checksum, 6)
    """
)


def test_two_process_data_parallel_train_step(tmp_path):
    spec, records = _run_two_process_entry(
        tmp_path, "dist_train_entry", TRAIN_ENTRY_SOURCE
    )
    import math

    for i, progress in enumerate(records):
        assert progress["process_count"] == spec.hosts
        assert progress["process_index"] == i
        assert progress["steps_done"] == 2
        assert math.isfinite(progress["loss"])
    # SPMD invariant: same loss observed and bit-identical param update
    # on both processes — the gradient psum really crossed the boundary.
    assert records[0]["loss"] == records[1]["loss"]
    assert records[0]["param_checksum"] == records[1]["param_checksum"]
