"""Fleet scheduler tests (runtime/fleet.py): throughput-optimal batch
placement on a hand-computable matrix, per-tenant quota enforcement,
priority preemption feeding the elastic-resume chain (one logical history
entry), backfill past a blocked queue head, capacity flaps, and
bit-identical decisions from a fixed seed.
"""

import itertools
import random
import time

import pytest

from cron_operator_tpu.runtime.fleet import (
    ANNOTATION_EST_WORK,
    ANNOTATION_FLEET_PLACED,
    ANNOTATION_PRIORITY,
    ANNOTATION_SLICE_TYPE,
    ANNOTATION_TENANT,
    ANNOTATION_WORKLOAD_CLASS,
    FleetScheduler,
    ThroughputMatrix,
    parse_pool,
    parse_quotas,
    plan_assignments,
)
from cron_operator_tpu.runtime.kube import APIServer
from cron_operator_tpu.runtime.manager import Metrics

JAX_AV, JAX_KIND = "kubeflow.org/v1", "JAXJob"
CRON_AV = "apps.kubedl.io/v1alpha1"


def wait_for(fn, timeout=30.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        result = fn()
        if result:
            return result
        time.sleep(interval)
    raise AssertionError("condition not met in time")


def make_job(name, wclass="w", namespace="default", priority=None,
             tenant=None, pinned_type=None, est_work=None, extra_ann=None):
    ann = {ANNOTATION_WORKLOAD_CLASS: wclass}
    if priority is not None:
        ann[ANNOTATION_PRIORITY] = str(priority)
    if tenant is not None:
        ann[ANNOTATION_TENANT] = tenant
    if pinned_type is not None:
        ann[ANNOTATION_SLICE_TYPE] = pinned_type
    if est_work is not None:
        ann[ANNOTATION_EST_WORK] = str(est_work)
    if extra_ann:
        ann.update(extra_ann)
    return {
        "apiVersion": JAX_AV,
        "kind": JAX_KIND,
        "metadata": {
            "namespace": namespace, "name": name, "annotations": ann,
        },
        "spec": {"replicaSpecs": {"Worker": {"replicas": 1, "template": {
            "spec": {"containers": [{"name": "train", "image": "x"}]},
        }}}},
    }


# The hand-computable 3-type / 5-job matrix from the issue: tokens/s per
# (workload class, slice type). The unique optimum places w2,w4 on v5e,
# w1,w3 on v4 and w5 on cpu for an aggregate 40.5 tokens/s — a greedy
# highest-rate-first pass would burn the v5e slots on w1 instead.
POOL3 = "v5e-16=2,v4-8=2,cpu=1"
RATES = {
    ("w1", "v5e-16"): 10.0, ("w1", "v4-8"): 9.0, ("w1", "cpu"): 1.0,
    ("w2", "v5e-16"): 10.0, ("w2", "v4-8"): 2.0, ("w2", "cpu"): 1.0,
    ("w3", "v5e-16"): 8.0, ("w3", "v4-8"): 7.0, ("w3", "cpu"): 6.0,
    ("w4", "v5e-16"): 9.0, ("w4", "v4-8"): 3.0, ("w4", "cpu"): 2.0,
    ("w5", "v5e-16"): 7.0, ("w5", "v4-8"): 6.0, ("w5", "cpu"): 5.5,
}
OPTIMAL = {"w1": "v4-8", "w2": "v5e-16", "w3": "v4-8",
           "w4": "v5e-16", "w5": "cpu"}


class TestPool:
    def test_parse_pool(self):
        pool = parse_pool(POOL3)
        by_name = {t.name: t for t in pool}
        assert by_name["v5e-16"].count == 2
        assert by_name["v5e-16"].chips == 16
        assert by_name["v5e-16"].spec.hosts == 4
        assert by_name["v4-8"].chips == 8
        assert by_name["cpu"].spec is None  # host-local capacity
        assert by_name["cpu"].chips == 1

    def test_parse_pool_rejects_garbage(self):
        with pytest.raises(ValueError):
            parse_pool("v5e-16=zero")
        with pytest.raises(ValueError):
            parse_pool("v5e-16=0")
        with pytest.raises(ValueError):
            parse_pool("  ,  ")

    def test_parse_pool_rejects_typod_tpu_shorthand(self):
        # A typo'd TPU shorthand must not silently become 1-chip
        # host-local capacity (the job would run unaccelerated, with no
        # topology stamping and no warning).
        with pytest.raises(ValueError):
            parse_pool("v5e-12=2")  # no such v5e shape
        with pytest.raises(ValueError):
            parse_pool("v4_8=4")  # misspelled separator
        # Names that don't lead with a TPU family still model host-local
        # capacity.
        pool = parse_pool("cpu=2,bigmem=1")
        assert all(t.spec is None for t in pool)

    def test_parse_quotas(self):
        assert parse_quotas(["team-a=32", "team-b=16"]) == {
            "team-a": 32, "team-b": 16,
        }
        with pytest.raises(ValueError):
            parse_quotas(["team-a"])

    def test_duplicate_type_rejected(self):
        with pytest.raises(ValueError):
            FleetScheduler(parse_pool("cpu=1,cpu=2"))


class TestThroughputMatrix:
    def test_seed_fallbacks(self):
        m = ThroughputMatrix({("w1", "a"): 4.0, ("*", "b"): 2.0})
        assert m.rate("w1", "a") == 4.0
        assert m.rate("w9", "b") == 2.0  # wildcard row
        assert m.rate("w9", "c", chips=16) == 16.0  # chips prior

    def test_observe_refines_online(self):
        m = ThroughputMatrix({("w1", "a"): 4.0}, alpha=0.5)
        m.observe("w1", "a", 8.0)
        assert m.rate("w1", "a") == pytest.approx(6.0)
        m.observe("w1", "a", "not-a-number")  # ignored, not fatal
        m.observe("w1", "a", -1)  # ignored
        assert m.rate("w1", "a") == pytest.approx(6.0)
        m.observe("w2", "a", 3.0)  # first observation seeds the cell
        assert m.rate("w2", "a") == pytest.approx(3.0)

    def test_load_seed_accepts_step_bench_sidecar(self, tmp_path):
        """hack/step_bench.py --emit-matrix-seed writes measured rates
        in the save() sidecar format; load_seed must read them back so a
        fresh operator's placement scorer starts from bench-measured
        throughput instead of the chips-proportional prior."""
        import importlib.util
        from pathlib import Path

        path = (Path(__file__).resolve().parent.parent
                / "hack" / "step_bench.py")
        spec = importlib.util.spec_from_file_location("step_bench", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)

        out = tmp_path / "fleet_matrix_seed.json"
        mod.write_matrix_seed(
            str(out), "cpu",
            {"train-small": 54874.3, "*": 54874.3,
             "train-large": 6043.5, "eval": None},  # unmeasured dropped
        )
        seed = ThroughputMatrix.load_seed(str(out))
        assert seed == {
            ("train-small", "cpu"): 54874.3,
            ("*", "cpu"): 54874.3,
            ("train-large", "cpu"): 6043.5,
        }
        m = ThroughputMatrix(seed)
        assert m.rate("train-small", "cpu") == 54874.3
        assert m.rate("preprocess", "cpu") == 54874.3  # "*" fallback row


class TestPlanAssignments:
    def test_matches_brute_force_optimum(self):
        """Regret-greedy must hit the exhaustive optimum on the issue's
        hand-computable matrix (and that optimum must be unique)."""
        jobs = [(f"w{i}", None, 0.0) for i in range(1, 6)]
        free = {"v5e-16": 2, "v4-8": 2, "cpu": 1}

        def rate(w, t):
            return RATES[(w, t)]

        plan = plan_assignments(jobs, free, rate)
        assert {j[0]: t for j, t in zip(jobs, plan)} == OPTIMAL
        best, best_count = 0.0, 0
        types = ["v5e-16"] * 2 + ["v4-8"] * 2 + ["cpu"]
        for perm in set(itertools.permutations(types)):
            total = sum(
                rate(f"w{i + 1}", t) for i, t in enumerate(perm)
            )
            if total > best + 1e-9:
                best, best_count = total, 1
            elif abs(total - best) <= 1e-9:
                best_count += 1
        assert best == pytest.approx(40.5)
        assert best_count == 1  # the hand-computed optimum is unique
        assert sum(
            rate(j[0], t) for j, t in zip(jobs, plan)
        ) == pytest.approx(best)

    def test_respects_pins_and_capacity(self):
        plan = plan_assignments(
            [("w1", "cpu", 0.0), ("w2", None, 0.0), ("w3", None, 0.0)],
            {"cpu": 1, "v4-8": 1},
            lambda w, t: {"cpu": 5.0, "v4-8": 1.0}[t],
        )
        # w1's pin takes the only cpu slot even though w2/w3 rate it
        # higher; exactly one of them lands on v4-8.
        assert plan[0] == "cpu"
        assert sorted(t for t in plan[1:] if t) == ["v4-8"]


class TestBatchDispatchOptimality:
    def test_queued_batch_lands_on_joint_optimum(self):
        """End-to-end via the wired path: saturate the pool, queue the
        five matrix jobs, free every slot at once — the dispatch batch
        must reproduce the joint optimum, not arrival-order greedy."""
        api = APIServer()
        metrics = Metrics()
        fs = FleetScheduler(
            parse_pool(POOL3), api=api,
            matrix=ThroughputMatrix(RATES), metrics=metrics,
        )
        api.add_watcher(fs._on_event, coalesce=True)
        fillers = [make_job(f"fill-{i}") for i in range(5)]
        for f in fillers:
            assert fs.submit(f).action == "placed"
        for i in range(1, 6):
            d = fs.submit(make_job(f"job-{i}", wclass=f"w{i}"))
            assert d.action == "queued"
        assert metrics.get(
            'cron_jobs_pending{backend="local",slice_type="v5e-16"}'
        ) is not None
        for f in fillers:
            meta = f["metadata"]
            api.patch_status(JAX_AV, JAX_KIND, meta["namespace"],
                             meta["name"], {"conditions": [{
                                 "type": "Succeeded", "status": "True",
                             }]})
        api.flush()
        fs.pump()
        placed = {
            key.split("/", 1)[1]: d["slice_type"]
            for key, d in fs.decision_log
            if d["action"] == "placed" and key.split("/", 1)[1].startswith(
                "job-")
        }
        assert placed == {
            f"job-{i}": OPTIMAL[f"w{i}"] for i in range(1, 6)
        }
        # Everything dispatched: pending gauge back to zero everywhere.
        for t in ("v5e-16", "v4-8", "cpu"):
            assert metrics.get(
                f'cron_jobs_pending{{backend="local",slice_type="{t}"}}'
            ) == 0.0
        api.close()


class TestQuotas:
    def test_tenant_quota_queues_despite_free_capacity(self):
        created = []
        fs = FleetScheduler(
            parse_pool("v5e-16=2"),
            quotas={"team-a": 16},
            on_create=lambda w, t: created.append(w),
        )
        a1 = fs.submit(make_job("a1", tenant="team-a"))
        assert a1.action == "placed"
        a2 = fs.submit(make_job("a2", tenant="team-a"))
        assert (a2.action, a2.reason) == ("queued", "saturated")
        # An unquota'd tenant takes the free slice the queued job cannot.
        assert fs.submit(make_job("b1", tenant="team-b")).action == "placed"
        assert fs.tenant_peak["team-a"] == 16
        fs.release("default", "a1")
        assert fs.stats()["queued"] == 0  # a2 dispatched into a1's slot
        assert [w["metadata"]["name"] for w in created] == [
            "a1", "b1", "a2",
        ]
        assert fs.tenant_peak["team-a"] == 16  # never exceeded

    def test_quota_binds_within_one_dispatch_batch(self):
        # Regression (caught by the capacity-flap soak): the batch
        # planner computed every job's headroom BEFORE any pick in the
        # band committed, so N same-tenant jobs could each claim the
        # same remaining budget and the batch overshot the quota.
        created = []
        fs = FleetScheduler(
            parse_pool("v4-8=4"),
            quotas={"team-a": 16},
            on_create=lambda w, t: created.append(w),
        )
        # Flap the whole pool away so the queue builds up, then restore
        # it: four slots open in ONE dispatch round, which plans the
        # three queued 8-chip team-a jobs jointly against a 16-chip
        # budget.
        assert fs.shrink_capacity("v4-8", 4) == 4
        for i in range(3):
            d = fs.submit(make_job(f"q-{i}", tenant="team-a"))
            assert d.action == "queued"
        assert fs.restore_capacity("v4-8") == 4
        assert fs.tenant_peak["team-a"] == 16  # two placed, never three
        assert fs.stats()["queued"] == 1
        assert len(created) == 2
        # Freed budget lets the straggler run (still within quota).
        assert fs.release("default", created[0]["metadata"]["name"])
        assert fs.stats()["queued"] == 0
        assert fs.tenant_peak["team-a"] == 16

    def test_quota_binds_across_preemption(self):
        fs = FleetScheduler(
            parse_pool("v5e-16=1"), quotas={"team-a": 16},
            on_create=lambda w, t: None,
        )
        assert fs.submit(
            make_job("low", tenant="team-a", priority="batch")
        ).action == "placed"
        # Same tenant, higher priority: preempting its own gang keeps the
        # quota whole, so the placement is allowed.
        d = fs.submit(make_job("hi", tenant="team-a", priority="high"))
        assert d.action == "placed"
        assert d.preempted == "default/low"
        assert fs.tenant_peak["team-a"] == 16


class TestPreemptionAndBackfill:
    def test_lower_priority_gang_is_preempted(self):
        preempts = []

        class FakeBackend:
            def preempt(self, ns, name, kind=None, api_version=None):
                preempts.append((ns, name))
                return {"lostDevices": 4, "jobFinished": False}

            def restore_capacity(self, n=None):
                preempts.append(("restore", n))

        fs = FleetScheduler(
            parse_pool("v5e-16=1"), backend=FakeBackend(),
            on_create=lambda w, t: None,
        )
        assert fs.submit(make_job("low", priority="batch")).action == "placed"
        d = fs.submit(make_job("hi", priority="high"))
        assert (d.action, d.preempted) == ("placed", "default/low")
        assert preempts == [("default", "low"), ("restore", 4)]
        assert fs.preempted_total == 1
        # Equal priority never preempts; it queues.
        assert fs.submit(
            make_job("hi2", priority="high")
        ).action == "queued"

    def test_backfill_past_blocked_head(self):
        fs = FleetScheduler(
            parse_pool("v5e-16=1,cpu=1"), on_create=lambda w, t: None,
        )
        assert fs.submit(
            make_job("holder", pinned_type="v5e-16")
        ).action == "placed"
        assert fs.submit(make_job("cpu-holder", wclass="wc")).action == \
            "placed"
        # Head of queue pinned to the busy v5e slice; the later job can
        # run anywhere.
        assert fs.submit(
            make_job("blocked-head", pinned_type="v5e-16")
        ).action == "queued"
        assert fs.submit(make_job("flex", wclass="wc")).action == "queued"
        fs.release("default", "cpu-holder")
        stats = fs.stats()
        assert stats["queued"] == 1  # flex dispatched, head still waiting
        assert fs.backfilled_total == 1
        backfills = [
            key for key, d in fs.decision_log if d["reason"] == "backfill"
        ]
        assert backfills == ["default/flex"]
        # Head dispatches (not backfill) once its pinned slice frees up.
        fs.release("default", "holder")
        assert fs.stats()["queued"] == 0
        assert fs.backfilled_total == 1

    def test_queue_overflow_rejects(self):
        fs = FleetScheduler(
            parse_pool("cpu=1"), max_queue=2,
            on_create=lambda w, t: None,
        )
        fs.submit(make_job("r0"))
        fs.submit(make_job("r1"))
        fs.submit(make_job("r2"))
        d = fs.submit(make_job("r3"))
        assert (d.action, d.reason) == ("rejected", "queue-full")
        assert fs.rejected_total == 1


class TestSubmitFaultPaths:
    def test_already_exists_keeps_books(self):
        """Fail-over replay: the workload already runs, so the
        reservation must stand (mirror of the _dispatch path) — undoing
        it would over-commit the slice type until the run terminates."""
        from cron_operator_tpu.runtime.kube import AlreadyExistsError

        api = APIServer()
        try:
            first = FleetScheduler(parse_pool("cpu=2"), api=api)
            assert first.submit(make_job("dup")).action == "placed"
            # New scheduler incarnation: empty books, same store.
            replay = FleetScheduler(parse_pool("cpu=2"), api=api)
            with pytest.raises(AlreadyExistsError):
                replay.submit(make_job("dup"))
            stats = replay.stats()
            assert stats["running"] == 1
            assert stats["free"]["cpu"] == 1
        finally:
            api.close()

    def test_create_failure_hands_slot_back_to_victim(self):
        """Preemption is deferred until the create lands: a transient
        create failure must not cost the victim a checkpoint/resume
        cycle for a displacing job that never materialized."""
        preempts = []

        class FakeBackend:
            def preempt(self, ns, name, kind=None, api_version=None):
                preempts.append((ns, name))
                return {"lostDevices": 4, "jobFinished": False}

            def restore_capacity(self, n=None):
                pass

        def creator(w, t):
            if w["metadata"]["name"] == "hi":
                raise RuntimeError("store down")

        fs = FleetScheduler(
            parse_pool("v5e-16=1"), backend=FakeBackend(),
            on_create=creator,
        )
        assert fs.submit(make_job("low", priority="batch")).action == \
            "placed"
        with pytest.raises(RuntimeError):
            fs.submit(make_job("hi", priority="high"))
        assert preempts == []  # the victim was never evicted
        assert fs.preempted_total == 0
        assert ("default", "low") in fs._running
        assert fs.stats()["free"]["v5e-16"] == 0
        # A later, healthy high-priority submit preempts as usual.
        assert fs.submit(
            make_job("hi2", priority="high")
        ).action == "placed"
        assert preempts == [("default", "low")]


class TestQueuedVisibility:
    def test_queued_for_and_cancel(self):
        fs = FleetScheduler(
            parse_pool("cpu=1"), on_create=lambda w, t: None,
        )
        assert fs.submit(make_job("holder")).action == "placed"
        tick = make_job("c-100")
        tick["metadata"]["labels"] = {"kubedl.io/cron-name": "c"}
        assert fs.submit(tick).action == "queued"
        assert [
            w["metadata"]["name"] for w in fs.queued_for("default", "c")
        ] == ["c-100"]
        assert fs.queued_for("default", "other") == []
        assert fs.queued_for("elsewhere", "c") == []
        assert fs.cancel("default", "c-100")
        assert not fs.cancel("default", "c-100")  # already gone
        assert fs.stats()["queued"] == 0
        # Cancel never touches running workloads.
        assert not fs.cancel("default", "holder")
        assert fs.stats()["running"] == 1


class TestCapacityFlap:
    def test_shrink_takes_free_slices_first(self):
        fs = FleetScheduler(
            parse_pool("v5e-16=2"), on_create=lambda w, t: None,
        )
        fs.submit(make_job("j1"))
        assert fs.shrink_capacity("v5e-16", 1) == 1
        assert fs.capacity("v5e-16") == 1
        assert fs.preempted_total == 0  # the free slice absorbed it
        # Next job queues against the shrunken pool, dispatches on grow.
        assert fs.submit(make_job("j2")).action == "queued"
        assert fs.restore_capacity("v5e-16") == 1
        assert fs.stats()["queued"] == 0

    def test_shrink_beyond_free_preempts_lowest_priority(self):
        fs = FleetScheduler(
            parse_pool("v5e-16=2"), on_create=lambda w, t: None,
        )
        fs.submit(make_job("hi", priority="high"))
        fs.submit(make_job("low", priority="batch"))
        assert fs.shrink_capacity("v5e-16", 1) == 1
        assert fs.preempted_total == 1
        assert ("default", "hi") in fs._running
        assert ("default", "low") not in fs._running
        # Flap cannot remove more than exists.
        assert fs.shrink_capacity("v5e-16", 5) == 1
        assert fs.capacity("v5e-16") == 0


class TestPins:
    def test_unpooled_pin_passes_through(self):
        created = []
        fs = FleetScheduler(
            parse_pool("cpu=1"),
            on_create=lambda w, t: created.append((w, t)),
        )
        d = fs.submit(make_job("exotic", extra_ann={
            "tpu.kubedl.io/accelerator": "tpu-v9-podslice",
            "tpu.kubedl.io/topology": "4x4",
        }))
        assert (d.action, d.reason) == ("placed", "unpooled-pin")
        assert created[0][1] is None  # untouched, untracked
        assert fs.stats()["running"] == 0

    def test_fleet_stamp_is_not_a_pin(self):
        """A resumed attempt inherits its predecessor's stamp; the marker
        makes it re-placeable instead of pinned to the old shape."""
        fs = FleetScheduler(
            parse_pool("v5e-16=1,v4-8=1"),
            matrix=ThroughputMatrix({("w", "v5e-16"): 1.0,
                                     ("w", "v4-8"): 9.0}),
            on_create=lambda w, t: None,
        )
        job = make_job("resume-r1", extra_ann={
            ANNOTATION_FLEET_PLACED: "true",
            "tpu.kubedl.io/accelerator": "tpu-v5-lite-podslice",
            "tpu.kubedl.io/topology": "4x4",
        })
        d = fs.submit(job)
        assert (d.action, d.slice_type) == ("placed", "v4-8")
        ann = job["metadata"]["annotations"]
        assert ann["tpu.kubedl.io/accelerator"] == "tpu-v4-podslice"
        assert ann[ANNOTATION_SLICE_TYPE] == "v4-8"

    def test_user_pin_placed_on_matching_pool_type(self):
        fs = FleetScheduler(
            parse_pool("v5e-16=1,v4-8=1"),
            matrix=ThroughputMatrix({("w", "v4-8"): 9.0}),
            on_create=lambda w, t: None,
        )
        job = make_job("pinned", extra_ann={
            "tpu.kubedl.io/accelerator": "tpu-v5-lite-podslice",
            "tpu.kubedl.io/topology": "4x4",
        })
        d = fs.submit(job)
        assert (d.action, d.slice_type) == ("placed", "v5e-16")
        # User-pinned: the template's own annotations stand (no marker).
        ann = job["metadata"]["annotations"]
        assert ANNOTATION_FLEET_PLACED not in ann


class TestDeterminism:
    def _drive(self, seed):
        rng = random.Random(seed)
        fs = FleetScheduler(
            parse_pool(POOL3), matrix=ThroughputMatrix(RATES),
            max_queue=64, on_create=lambda w, t: None,
        )
        live = []
        for i in range(60):
            wclass = f"w{rng.randint(1, 5)}"
            prio = rng.choice(["high", "normal", "normal", "batch"])
            d = fs.submit(make_job(f"j{i}", wclass=wclass, priority=prio,
                                   tenant=rng.choice(["ta", "tb"])))
            if d.action != "rejected":
                live.append(f"j{i}")
            if live and rng.random() < 0.4:
                fs.release("default", live.pop(rng.randrange(len(live))))
            if rng.random() < 0.05:
                fs.shrink_capacity(rng.choice(["v5e-16", "v4-8"]), 1)
            if rng.random() < 0.05:
                fs.restore_capacity()
        return list(fs.decision_log)

    def test_same_seed_same_decisions(self):
        assert self._drive(42) == self._drive(42)

    def test_decision_log_is_nonempty_and_varied(self):
        log = self._drive(42)
        actions = {d["action"] for _k, d in log}
        assert "placed" in actions and "queued" in actions


@pytest.mark.slow
class TestPreemptElasticResumeEndToEnd:
    def test_preempted_job_resumes_with_one_history_entry(self):
        """Priority preemption through the real executor: the victim
        fails with the Preempted marker, the controller's elastic chain
        resumes it through the fleet (queued until the aggressor
        finishes), and history collapses to ONE logical entry."""
        from cron_operator_tpu.backends.local import LocalExecutor
        from cron_operator_tpu.controller.cron_controller import (
            CronReconciler,
        )

        api = APIServer()
        metrics = Metrics()
        ex = LocalExecutor(api, metrics=metrics)
        ex.start()
        fs = FleetScheduler(
            parse_pool("cpu=1"), api=api, backend=ex, metrics=metrics,
        ).start()
        rec = CronReconciler(api, metrics=metrics, fleet=fs)
        try:
            def mkcron(name, priority, duration, elastic):
                ann = {
                    "tpu.kubedl.io/simulate-duration": duration,
                    ANNOTATION_PRIORITY: priority,
                }
                if elastic:
                    ann["tpu.kubedl.io/elastic-resume"] = "true"
                api.create({
                    "apiVersion": CRON_AV, "kind": "Cron",
                    "metadata": {"name": name, "namespace": "default"},
                    "spec": {
                        "schedule": "@every 1s",
                        "concurrencyPolicy": "Forbid",
                        "suspend": False,
                        "template": {"workload": {
                            "apiVersion": JAX_AV, "kind": JAX_KIND,
                            "metadata": {"annotations": ann},
                            "spec": {},
                        }},
                    },
                })

            mkcron("victim", "batch", "6s", elastic=True)

            def fire(name):
                rec.reconcile("default", name)
                return [
                    j for j in api.list(JAX_AV, JAX_KIND,
                                        namespace="default")
                    if j["metadata"].get("labels", {}).get(
                        "tpu.kubedl.io/cron-name") == name
                    or j["metadata"]["name"].startswith(name)
                ]

            jobs = wait_for(lambda: fire("victim"), timeout=15.0,
                            interval=0.3)
            root = jobs[0]["metadata"]["name"]
            wait_for(lambda: "Running" in [
                c["type"] for c in (api.get(
                    JAX_AV, JAX_KIND, "default", root
                ).get("status") or {}).get("conditions", [])
            ])

            mkcron("aggressor", "high", "0.3s", elastic=False)
            wait_for(lambda: fire("aggressor"), timeout=15.0, interval=0.3)
            assert fs.preempted_total == 1

            # Park the aggressor so its next ticks don't keep preempting
            # the batch-priority resume (starvation is WAI under strict
            # priorities; this test is about the elastic chain).
            import copy as _copy

            agg = _copy.deepcopy(
                api.get(CRON_AV, "Cron", "default", "aggressor")
            )
            agg["spec"]["suspend"] = True
            api.update(agg)

            # The victim's resume rides the normal reconcile sweep; it
            # queues behind the aggressor and dispatches when the slice
            # frees. Drive the victim until the logical run completes.
            def resumed_done():
                rec.reconcile("default", "victim")
                rname = f"{root}-r1"
                obj = api.try_get(JAX_AV, JAX_KIND, "default", rname)
                if obj is None:
                    return False
                conds = (obj.get("status") or {}).get("conditions") or []
                return bool(conds) and conds[-1]["type"] == "Succeeded"

            wait_for(resumed_done, timeout=60.0, interval=0.3)
            rec.reconcile("default", "victim")

            from cron_operator_tpu.api.v1alpha1 import Cron
            cron = Cron.from_dict(
                api.get(CRON_AV, "Cron", "default", "victim")
            )
            hist = cron.status.history
            assert len(hist) == 1  # ONE logical run, not two attempts
            assert hist[0].status == "Succeeded"
            assert hist[0].resumes == 1
            assert hist[0].object.name == root
            assert metrics.get("cron_workload_resumes_total") == 1.0
            assert metrics.get("fleet_preemptions_total") == 1.0
        finally:
            fs.stop()
            ex.stop()
            api.close()


class TestControllerWiring:
    def test_submit_workload_routes_through_fleet(self):
        from cron_operator_tpu.controller.cron_controller import (
            CronReconciler,
        )

        api = APIServer()
        fs = FleetScheduler(parse_pool("cpu=1"), api=api)
        rec = CronReconciler(api, fleet=fs)
        api.create({
            "apiVersion": CRON_AV, "kind": "Cron",
            "metadata": {"name": "c", "namespace": "default"},
            "spec": {
                "schedule": "@every 1s",
                "template": {"workload": {
                    "apiVersion": JAX_AV, "kind": JAX_KIND,
                    "metadata": {"annotations": {}}, "spec": {},
                }},
            },
        })
        wait_for(lambda: (
            rec.reconcile("default", "c"),
            api.list(JAX_AV, JAX_KIND, namespace="default"),
        )[1], timeout=15.0, interval=0.3)
        # The created workload carries the fleet stamp — proof the create
        # went through fleet.submit, not straight api.create.
        job = api.list(JAX_AV, JAX_KIND, namespace="default")[0]
        ann = job["metadata"]["annotations"]
        assert ann[ANNOTATION_SLICE_TYPE] == "cpu"
        assert fs.stats()["running"] == 1
        api.close()

    @staticmethod
    def _make_fleet_cron(api, name, policy):
        api.create({
            "apiVersion": CRON_AV, "kind": "Cron",
            "metadata": {"name": name, "namespace": "default"},
            "spec": {
                "schedule": "*/1 * * * *",
                "concurrencyPolicy": policy,
                "template": {"workload": {
                    "apiVersion": JAX_AV, "kind": JAX_KIND,
                    "metadata": {"annotations": {}}, "spec": {},
                }},
            },
        })

    def test_forbid_sees_fleet_queued_tick(self):
        """A tick queued in the fleet's books is invisible to the store
        list — the Forbid gate must still count it as active, or tick N
        (queued) and tick N+1 (fired) dispatch concurrently once
        capacity frees."""
        from datetime import timedelta

        from cron_operator_tpu.controller.cron_controller import (
            CronReconciler,
        )
        from cron_operator_tpu.utils.clock import FakeClock

        clock = FakeClock()
        api = APIServer(clock=clock)
        try:
            fs = FleetScheduler(parse_pool("cpu=1"), api=api)
            metrics = Metrics()
            rec = CronReconciler(api, metrics=metrics, fleet=fs)
            fs.submit(make_job("holder"))  # saturate the pool
            self._make_fleet_cron(api, "fb", "Forbid")
            clock.advance(timedelta(seconds=61))
            rec.reconcile("default", "fb")
            assert fs.stats()["queued"] == 1  # tick N admitted, queued
            clock.advance(timedelta(seconds=60))
            rec.reconcile("default", "fb")
            # Tick N+1 must not pass the gate while tick N waits.
            assert fs.stats()["queued"] == 1
            assert metrics.get(
                'cron_ticks_skipped_total{policy="Forbid"}'
            ) == 1.0
            assert metrics.get("cron_ticks_fired_total") == 1.0
        finally:
            api.close()

    def test_replace_cancels_fleet_queued_tick(self):
        """Replace's delete-all-active cannot reach a tick that exists
        only in the fleet's books — it must cancel it there, or the
        stale replaced tick still dispatches later."""
        from datetime import timedelta

        from cron_operator_tpu.controller.cron_controller import (
            CronReconciler,
        )
        from cron_operator_tpu.utils.clock import FakeClock

        clock = FakeClock()
        api = APIServer(clock=clock)
        try:
            fs = FleetScheduler(parse_pool("cpu=1"), api=api)
            metrics = Metrics()
            rec = CronReconciler(api, metrics=metrics, fleet=fs)
            fs.submit(make_job("holder"))  # saturate the pool
            self._make_fleet_cron(api, "rp", "Replace")
            clock.advance(timedelta(seconds=61))
            rec.reconcile("default", "rp")
            q1 = fs.queued_for("default", "rp")
            assert len(q1) == 1
            stale = q1[0]["metadata"]["name"]
            clock.advance(timedelta(seconds=60))
            rec.reconcile("default", "rp")
            q2 = [w["metadata"]["name"]
                  for w in fs.queued_for("default", "rp")]
            assert len(q2) == 1 and q2 != [stale]  # superseding tick only
            assert metrics.get("cron_workloads_replaced_total") == 1.0
            # The cancelled tick can no longer dispatch.
            fs.release("default", "holder")
            names = {
                (w.get("metadata") or {}).get("name")
                for w in api.list(JAX_AV, JAX_KIND, namespace="default")
            }
            assert stale not in names
            assert q2[0] in names
        finally:
            api.close()

    def test_rejected_tick_records_warning_event(self):
        from cron_operator_tpu.controller.cron_controller import (
            CronReconciler,
        )

        api = APIServer()
        fs = FleetScheduler(parse_pool("cpu=1"), api=api, max_queue=0)
        metrics = Metrics()
        rec = CronReconciler(api, metrics=metrics, fleet=fs)
        fs.submit(make_job("holder"))  # saturate: queue depth 0 → shed
        api.create({
            "apiVersion": CRON_AV, "kind": "Cron",
            "metadata": {"name": "shed", "namespace": "default"},
            "spec": {
                "schedule": "@every 1s",
                "template": {"workload": {
                    "apiVersion": JAX_AV, "kind": JAX_KIND,
                    "metadata": {"annotations": {}}, "spec": {},
                }},
            },
        })

        def shed_event():
            rec.reconcile("default", "shed")
            return [
                e for e in api.list("v1", "Event", namespace="default")
                if e.get("reason") == "FleetRejected"
            ]

        events = wait_for(shed_event, timeout=15.0, interval=0.3)
        assert events
        assert fs.rejected_total >= 1
        # A shed tick is NOT a fired tick: no workload was or will be
        # created, so the fired counter must not misreport it.
        assert metrics.get("cron_ticks_fired_total") == 0.0
        api.close()


class TestGrowPlanner:
    """Bidirectional elasticity at the fleet layer: sustained-idle grow
    via planned reconfigure, shrink-back of grown gangs under priority
    pressure, and the @chips host-local pool syntax that models width
    tiers for the grow soak."""

    ELASTIC = {"tpu.kubedl.io/elastic-resume": "true"}

    class RecordingBackend:
        def __init__(self):
            self.reconfigures = []
            self.preempts = []

        def reconfigure(self, ns, name, kind=None, api_version=None,
                        target_devices=0, reason=""):
            self.reconfigures.append((ns, name, target_devices, reason))
            return {"targetDevices": target_devices, "reason": reason}

        def preempt(self, ns, name, kind=None, api_version=None):
            self.preempts.append((ns, name))
            return {"lostDevices": 1, "jobFinished": False}

        def restore_capacity(self, n=None):
            pass

    def _fleet(self, pool, **kw):
        be = self.RecordingBackend()
        kw.setdefault("grow_enabled", True)
        kw.setdefault("grow_idle_pumps", 3)
        fs = FleetScheduler(
            parse_pool(pool), backend=be, on_create=lambda w, t: None, **kw
        )
        return fs, be

    def test_parse_pool_host_chips(self):
        pool = {t.name: t for t in parse_pool("cpu-small=1@2,cpu-wide=2@8")}
        assert pool["cpu-small"].chips == 2
        assert pool["cpu-wide"].chips == 8
        assert pool["cpu-wide"].count == 2
        assert pool["cpu-wide"].spec is None  # still host-local

    def test_parse_pool_host_chips_rejects_garbage(self):
        with pytest.raises(ValueError):
            parse_pool("v5e-16=1@4")  # TPU shapes fix their own chips
        with pytest.raises(ValueError):
            parse_pool("cpu=1@0")
        with pytest.raises(ValueError):
            parse_pool("cpu=1@x")

    def _grow_setup(self, **kw):
        """Elastic gang on the narrow slice, wide slice just freed."""
        fs, be = self._fleet("cpu-small=1@2,cpu-wide=1@8", **kw)
        assert fs.submit(make_job("blocker")).action == "placed"
        assert fs.submit(
            make_job("growme", extra_ann=self.ELASTIC)
        ).action == "placed"
        # Chips-proportional prior: blocker grabbed the 8-chip slice.
        fs.release("default", "blocker")
        return fs, be

    def test_grow_fires_after_sustained_idle(self):
        fs, be = self._grow_setup()
        fs.pump()
        fs.pump()
        assert be.reconfigures == []  # hysteresis window not yet met
        fs.pump()
        assert be.reconfigures == [("default", "growme", 8, "FleetGrow")]
        assert fs.grows_total == 1
        assert fs.stats()["grows_total"] == 1
        # The gang's slot was handed back; the resume re-enters through
        # submit() like any other gang.
        assert fs.stats()["free"] == {"cpu-small": 1, "cpu-wide": 1}

    def test_grow_streak_resets_on_queued_work(self):
        fs, be = self._grow_setup()
        fs.pump()
        fs.pump()
        # Queued work has first claim on the idle slice: streak resets.
        assert fs.submit(
            make_job("wait", pinned_type="cpu-small")
        ).action == "queued"
        for _ in range(5):
            fs.pump()
        assert be.reconfigures == []
        assert fs.grows_total == 0

    def test_grow_respects_min_gain(self):
        fs, be = self._grow_setup(grow_min_gain=100.0)
        for _ in range(6):
            fs.pump()
        assert be.reconfigures == []

    def test_grow_disabled_by_default(self):
        fs, be = self._fleet("cpu-small=1@2,cpu-wide=1@8",
                             grow_enabled=False)
        fs.submit(make_job("blocker"))
        fs.submit(make_job("growme", extra_ann=self.ELASTIC))
        fs.release("default", "blocker")
        for _ in range(6):
            fs.pump()
        assert be.reconfigures == []

    def test_grow_skips_pinned_gangs(self):
        fs, be = self._fleet("cpu-small=1@2,cpu-wide=1@8")
        ann = dict(self.ELASTIC)
        fs.submit(make_job("pinned", pinned_type="cpu-small",
                           extra_ann=ann))
        for _ in range(6):
            fs.pump()
        assert be.reconfigures == []

    def test_grow_skips_non_elastic_gangs(self):
        fs, be = self._fleet("cpu-small=1@2,cpu-wide=1@8")
        fs.submit(make_job("blocker"))
        fs.submit(make_job("rigid"))  # no elastic-resume annotation
        fs.release("default", "blocker")
        for _ in range(6):
            fs.pump()
        assert be.reconfigures == []

    def test_shrink_back_on_priority_pressure(self):
        """A previously-grown gang under pressure returns to its original
        width via planned reconfigure (FleetShrink) — not Preempted."""
        fs, be = self._fleet("cpu-wide=1@8")
        grown_ann = dict(self.ELASTIC)
        grown_ann["tpu.kubedl.io/resume-cause"] = "grow"
        grown_ann["tpu.kubedl.io/original-devices"] = "2"
        d = fs.submit(make_job("grown", priority="batch",
                               extra_ann=grown_ann))
        assert d.action == "placed"
        d = fs.submit(make_job("hi", priority="high"))
        assert (d.action, d.preempted) == ("placed", "default/grown")
        assert be.reconfigures == [("default", "grown", 2, "FleetShrink")]
        assert be.preempts == []  # planned path, not preemption
        assert fs.shrinks_total == 1
        assert fs.preempted_total == 0
        assert fs.stats()["shrinks_total"] == 1

    def test_grown_gang_is_preferred_victim(self):
        """Among equal-priority victims the grown gang goes first: its
        eviction is the cheap one (shrink-back reclaims loaned width)."""
        fs, be = self._fleet("cpu-wide=2@8")
        grown_ann = dict(self.ELASTIC)
        grown_ann["tpu.kubedl.io/resume-cause"] = "grow"
        grown_ann["tpu.kubedl.io/original-devices"] = "2"
        fs.submit(make_job("plain", priority="batch"))
        fs.submit(make_job("grown", priority="batch", extra_ann=grown_ann))
        d = fs.submit(make_job("hi", priority="high"))
        assert (d.action, d.preempted) == ("placed", "default/grown")
        assert be.reconfigures == [("default", "grown", 2, "FleetShrink")]
        assert be.preempts == []

    def test_stats_grown_reports_reclaimed_width(self):
        fs, be = self._fleet("cpu-wide=1@8")
        grown_ann = dict(self.ELASTIC)
        grown_ann["tpu.kubedl.io/resume-cause"] = "grow"
        grown_ann["tpu.kubedl.io/original-devices"] = "2"
        fs.submit(make_job("grown", extra_ann=grown_ann))
        assert fs.stats()["grown"] == {"default/grown": 6}
