"""Flight recorder (telemetry/audit.py): unit behavior of the bounded
ring + WAL cross-check aggregates, the store/controller integration that
makes audit ≡ WAL hold record for record (invariant I9's store leg), and
the ``/debug/audit`` / ``/debug/shards`` HTTP surface."""

from __future__ import annotations

import json
import urllib.request
from datetime import timedelta

import pytest

from cron_operator_tpu.api.v1alpha1 import LABEL_CRON_NAME
from cron_operator_tpu.backends.tpu import ANNOTATION_ELASTIC_RESUME
from cron_operator_tpu.controller import CronReconciler
from cron_operator_tpu.runtime.manager import Metrics
from cron_operator_tpu.runtime.persistence import Persistence
from cron_operator_tpu.telemetry import ANNOTATION_TRACE_ID, AuditJournal
from cron_operator_tpu.telemetry.audit import object_key

CRON_API = "apps.kubedl.io/v1alpha1"
WL_API = "kubeflow.org/v1"
WL_KIND = "JAXJob"


def _cron(name="demo", schedule="*/5 * * * *", policy=None):
    spec = {
        "schedule": schedule,
        "template": {"workload": {
            "apiVersion": WL_API, "kind": WL_KIND,
            "spec": {"replicaSpecs": {"Worker": {"replicas": 1}}},
        }},
    }
    if policy:
        spec["concurrencyPolicy"] = policy
    return {
        "apiVersion": CRON_API, "kind": "Cron",
        "metadata": {"name": name, "namespace": "default"},
        "spec": spec,
    }


class TestJournalUnit:
    def test_record_seq_kind_totals_and_total(self):
        j = AuditJournal()
        j.record("store", "create", key="a/b/ns/x", wal_pos=1, rv=1)
        j.record("decision", "tick_fired", key="a/b/ns/x", trace_id="t-1")
        j.record("cluster", "lease_acquired", reason="op-1")
        assert j.total == 3
        assert j.kind_totals() == {"store": 1, "decision": 1, "cluster": 1}
        recs = j.records()
        assert [r["seq"] for r in recs] == [1, 2, 3]
        assert recs[1]["trace_id"] == "t-1"
        assert recs[2]["reason"] == "op-1"

    def test_filters_and_limit_keeps_newest(self):
        j = AuditJournal()
        for i in range(10):
            j.record("store", "update", key=f"a/b/ns/obj-{i}",
                     shard=i % 2, trace_id=f"t-{i % 3}")
        assert len(j.records(kind="store")) == 10
        assert len(j.records(kind="decision")) == 0
        assert len(j.records(shard=1)) == 5
        assert len(j.records(trace_id="t-0")) == 4
        assert [r["key"] for r in j.records(key_contains="obj-7")] \
            == ["a/b/ns/obj-7"]
        # limit keeps the NEWEST matches — the tail of a flight recorder
        tail = j.records(limit=3)
        assert [r["seq"] for r in tail] == [8, 9, 10]

    def test_ring_bounded_eviction_counted_totals_exact(self):
        m = Metrics()
        j = AuditJournal(max_records=4, metrics=m)
        for i in range(10):
            j.record("decision", "tick_fired", key=f"k-{i}")
        assert len(j.records()) == 4
        assert j.records_dropped == 6
        assert m.get("audit_records_dropped_total") == 6
        # per-kind totals and total survive eviction
        assert j.total == 10
        assert j.kind_totals() == {"decision": 10}
        assert m.get('audit_records_total{kind="decision"}') == 10

    def test_jsonl_sink_outlives_ring(self, tmp_path):
        path = str(tmp_path / "audit.jsonl")
        j = AuditJournal(max_records=2, sink_path=path)
        for i in range(5):
            j.record("store", "create", key=f"k-{i}", wal_pos=i + 1)
        j.close()
        lines = [json.loads(line)
                 for line in open(path) if line.strip()]
        assert [r["seq"] for r in lines] == [1, 2, 3, 4, 5]
        assert lines[0]["key"] == "k-0"  # evicted from ring, on tape
        assert len(j.records()) == 2

    def test_render_json_filters_and_default_bound(self):
        j = AuditJournal()
        for i in range(300):
            j.record("store", "update", key=f"k-{i}")
        j.record("decision", "tick_fired", key="k-x", trace_id="t-z")
        doc = json.loads(j.render_json({}))
        assert doc["total"] == 301
        assert doc["matched"] == 256  # default limit bounds the body
        assert len(doc["records"]) == 256
        doc = json.loads(j.render_json(
            {"kind": ["decision"], "trace": ["t-z"], "limit": ["5"]}
        ))
        assert doc["matched"] == 1
        assert doc["records"][0]["event"] == "tick_fired"
        # malformed params degrade, never raise
        doc = json.loads(j.render_json(
            {"shard": ["bogus"], "limit": ["many"]}
        ))
        assert doc["matched"] == 256

    def test_shard_view_stamps_and_delegates(self):
        j = AuditJournal()
        v = j.shard_view(3)
        v.record("store", "create", key="k", wal_pos=1)
        (rec,) = j.records()
        assert rec["shard"] == 3
        # explicit shard wins over the view's stamp
        v.record("cluster", "shard_failover", shard=7)
        assert j.records()[-1]["shard"] == 7
        # delegation: the view answers the whole journal surface
        assert v.total == 2
        assert v.wal_check(1, shard=3)["ok"]

    def test_object_key(self):
        assert object_key({
            "apiVersion": CRON_API, "kind": "Cron",
            "metadata": {"namespace": "ns", "name": "x"},
        }) == f"{CRON_API}/Cron/ns/x"
        assert object_key({}) == "///"


class TestWalCrossCheck:
    def test_contiguous_stream_passes(self):
        j = AuditJournal()
        for i in range(1, 6):
            j.record("store", "update", key="k", wal_pos=i)
        check = j.wal_check(5)
        assert check["ok"]
        assert check["audited_records"] == 5
        assert check["unaudited_tail"] == 0

    def test_gap_in_positions_fails(self):
        j = AuditJournal()
        j.record("store", "update", key="k", wal_pos=1)
        j.record("store", "update", key="k", wal_pos=3)  # 2 missing
        check = j.wal_check(3)
        assert not check["ok"]
        assert not check["contiguous"]

    def test_wal_ahead_of_audit_fails_without_crash_tail(self):
        j = AuditJournal()
        j.record("store", "update", key="k", wal_pos=1)
        assert not j.wal_check(2)["ok"]          # durable but unaudited
        assert j.wal_check(2, crash_tail=1)["ok"]  # kill mid-commit
        assert not j.wal_check(3, crash_tail=1)["ok"]  # only ONE in flight

    def test_audit_ahead_of_wal_fails(self):
        j = AuditJournal()
        j.record("store", "update", key="k", wal_pos=1)
        j.record("store", "update", key="k", wal_pos=2)
        assert not j.wal_check(1)["ok"]  # audited verb never durable

    def test_stream_must_start_at_one(self):
        j = AuditJournal()
        j.record("store", "update", key="k", wal_pos=2)
        assert not j.wal_check(2)["ok"]

    def test_empty_journal_matches_empty_wal_only(self):
        j = AuditJournal()
        assert j.wal_check(0)["ok"]
        assert not j.wal_check(4)["ok"]

    def test_reset_wal_judges_the_new_wal(self):
        j = AuditJournal()
        v = j.shard_view(0)
        for i in range(1, 4):
            v.record("store", "update", key="k", wal_pos=i)
        assert j.wal_check(3, shard=0)["ok"]
        # failover: fresh Persistence restarts the position counter
        j.reset_wal(0)
        v.record("store", "update", key="k", wal_pos=1)
        check = j.wal_check(1, shard=0)
        assert check["ok"]
        assert check["audited_records"] == 1

    def test_per_shard_streams_are_independent(self):
        j = AuditJournal()
        a, b = j.shard_view(0), j.shard_view(1)
        a.record("store", "update", key="k", wal_pos=1)
        b.record("store", "update", key="k", wal_pos=1)
        b.record("store", "update", key="k", wal_pos=2)
        assert j.wal_check(1, shard=0)["ok"]
        assert j.wal_check(2, shard=1)["ok"]
        assert not j.wal_check(2, shard=0)["ok"]


class TestStoreIntegration:
    """Every committed verb audited, under the same lock as its WAL
    append — the property wal_check certifies."""

    @pytest.fixture
    def stack(self, api, tmp_path):
        journal = AuditJournal()
        pers = Persistence(str(tmp_path), flush_interval_s=0)
        pers.attach_audit(journal)
        pers.start(api)
        api.attach_audit(journal)
        yield api, pers, journal
        pers.close()

    def test_verbs_audited_contiguously_and_match_wal(self, stack):
        api, pers, journal = stack
        api.create(_cron("a"))
        api.create(_cron("b"))
        obj = api.get(CRON_API, "Cron", "default", "a")
        obj = dict(obj)
        obj["metadata"] = dict(obj["metadata"],
                               labels={"touched": "yes"})
        api.update(obj)
        api.patch_status(CRON_API, "Cron", "default", "b",
                         {"lastScheduleTime": "2026-01-01T00:00:00Z"})
        api.delete(CRON_API, "Cron", "default", "a")

        events = [r["event"] for r in journal.records(kind="store")]
        assert events == ["create", "create", "update", "patch_status",
                          "delete"]
        check = journal.wal_check(pers.records_appended)
        assert check["ok"], check
        # each record carries the committed rv and its WAL position
        recs = journal.records(kind="store")
        assert [r["wal_pos"] for r in recs] == [1, 2, 3, 4, 5]
        assert all(r["rv"] is not None for r in recs)

    def test_noop_status_patch_not_audited(self, stack):
        api, pers, journal = stack
        api.create(_cron("a"))
        api.patch_status(CRON_API, "Cron", "default", "a",
                         {"benchSeq": "steady"})
        before = journal.total
        wal_before = pers.records_appended
        for _ in range(10):
            api.patch_status(CRON_API, "Cron", "default", "a",
                             {"benchSeq": "steady"})
        assert journal.total == before       # elided before the journal
        assert pers.records_appended == wal_before  # and before the WAL
        assert journal.wal_check(pers.records_appended)["ok"]

    def test_trace_id_from_annotation_lands_on_record(self, stack):
        api, pers, journal = stack
        wl = {
            "apiVersion": WL_API, "kind": WL_KIND,
            "metadata": {
                "name": "j", "namespace": "default",
                "annotations": {ANNOTATION_TRACE_ID: "cafe0123deadbeef"},
            },
            "spec": {"replicaSpecs": {"Worker": {"replicas": 1}}},
        }
        api.create(wl)
        (rec,) = journal.records(kind="store", event="create")
        assert rec["trace_id"] == "cafe0123deadbeef"
        assert rec["key"] == f"{WL_API}/{WL_KIND}/default/j"


class TestControllerDecisions:
    def test_tick_fired_audited_with_workload_trace_id(
        self, api, fake_clock
    ):
        journal = AuditJournal()
        api.attach_audit(journal)
        rec = CronReconciler(api, audit=journal)
        api.create(_cron())
        fake_clock.advance(timedelta(minutes=10))
        rec.reconcile("default", "demo")

        (fired,) = journal.records(kind="decision", event="tick_fired")
        (job,) = api.list(WL_API, WL_KIND, namespace="default")
        assert fired["trace_id"] \
            == job["metadata"]["annotations"][ANNOTATION_TRACE_ID]
        assert fired["key"].endswith(job["metadata"]["name"])
        # the submit decision shares the tick's trace id
        (submit,) = journal.records(kind="decision", event="submit")
        assert submit["trace_id"] == fired["trace_id"]

    def test_tick_skipped_forbid_audited_with_reason(
        self, api, fake_clock
    ):
        journal = AuditJournal()
        rec = CronReconciler(api, audit=journal)
        api.create(_cron(policy="Forbid"))
        fake_clock.advance(timedelta(minutes=5))
        rec.reconcile("default", "demo")  # fires; workload stays active
        fake_clock.advance(timedelta(minutes=5))
        rec.reconcile("default", "demo")  # Forbid: active run blocks it

        (skip,) = journal.records(kind="decision", event="tick_skipped")
        assert skip["reason"] == "Forbid"
        assert len(journal.records(event="tick_fired")) == 1

    def test_resume_decision_audited_with_lineage(self, api, fake_clock):
        journal = AuditJournal()
        rec = CronReconciler(api, audit=journal)
        api.create(_cron(schedule="0 0 1 1 *"))  # no tick due today
        api.create({
            "apiVersion": WL_API, "kind": WL_KIND,
            "metadata": {
                "name": "demo-run", "namespace": "default",
                "labels": {LABEL_CRON_NAME: "demo"},
                "annotations": {
                    ANNOTATION_ELASTIC_RESUME: "true",
                    ANNOTATION_TRACE_ID: "feed0123deadbeef",
                },
            },
            "spec": {"replicaSpecs": {"Worker": {"replicas": 8}}},
        })
        api.patch_status(WL_API, WL_KIND, "default", "demo-run", {
            "conditions": [
                {"type": "Preempted", "status": "True",
                 "reason": "TPUSlicePreempted"},
                {"type": "Failed", "status": "True",
                 "reason": "TPUSlicePreempted"},
            ],
            "preemption": {"survivingDevices": 4, "priorDevices": 8},
        })
        rec.reconcile("default", "demo")

        (resume,) = journal.records(kind="decision", event="resume")
        assert resume["reason"] == "TPUSlicePreempted"
        assert resume["key"].endswith("demo-run-r1")
        # lineage: the successor carries (and the record names) the
        # ROOT attempt's trace id
        assert resume["trace_id"] == "feed0123deadbeef"
        assert resume["attrs"]["root"] == "demo-run"
        assert resume["attrs"]["attempt"] == 1


class TestDebugEndpoints:
    """The HTTP surface: filter params, bounded bodies, content types,
    and the empty-store shape."""

    def _get(self, port, path):
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=5
        ) as resp:
            return resp.headers["Content-Type"], resp.read().decode()

    def test_debug_audit_params_bound_and_content_type(self):
        from cron_operator_tpu.cli.main import _serve

        journal = AuditJournal()
        for i in range(300):
            journal.record("store", "update", key=f"k-{i}", shard=0)
        journal.record("decision", "tick_fired", key="cron/x",
                       trace_id="t-q", shard=1)
        server = _serve(
            0,
            {"/debug/audit": lambda params: (
                journal.render_json(params), "application/json")},
            "test-audit",
        )
        try:
            port = server.server_address[1]
            ctype, body = self._get(port, "/debug/audit")
            assert ctype == "application/json"
            doc = json.loads(body)
            assert doc["total"] == 301
            assert doc["matched"] == 256  # default limit bounds the body

            _, body = self._get(
                port, "/debug/audit?kind=decision&trace=t-q&limit=10"
            )
            doc = json.loads(body)
            assert doc["matched"] == 1
            assert doc["records"][0]["event"] == "tick_fired"

            _, body = self._get(port, "/debug/audit?shard=1")
            assert json.loads(body)["matched"] == 1

            _, body = self._get(port, "/debug/audit?limit=7")
            doc = json.loads(body)
            assert len(doc["records"]) == 7
            # newest tail: the decision record is the last one
            assert doc["records"][-1]["kind"] == "decision"
        finally:
            server.shutdown()

    def test_debug_audit_empty_store(self):
        from cron_operator_tpu.cli.main import _serve

        journal = AuditJournal()
        server = _serve(
            0,
            {"/debug/audit": lambda params: (
                journal.render_json(params), "application/json")},
            "test-audit-empty",
        )
        try:
            port = server.server_address[1]
            ctype, body = self._get(port, "/debug/audit?kind=store")
            assert ctype == "application/json"
            doc = json.loads(body)
            assert doc == {"total": 0, "dropped": 0, "kind_totals": {},
                           "matched": 0, "records": []}
        finally:
            server.shutdown()

    def test_debug_shards_shape(self, tmp_path):
        from cron_operator_tpu.cli.main import _serve
        from cron_operator_tpu.runtime.shard import ShardedControlPlane

        plane = ShardedControlPlane(
            n_shards=2, data_dir=str(tmp_path), flush_interval_s=0
        )
        try:
            plane.router.create(_cron("alpha"))
            plane.router.create(_cron("beta"))
            server = _serve(
                0,
                {"/debug/shards": lambda: (
                    plane.render_debug_json(), "application/json")},
                "test-shards",
            )
            try:
                port = server.server_address[1]
                ctype, body = self._get(port, "/debug/shards")
                assert ctype == "application/json"
                doc = json.loads(body)
                assert doc["n_shards"] == 2
                assert len(doc["shards"]) == 2
                for entry in doc["shards"]:
                    assert {"shard", "objects", "rv", "failovers",
                            "leader", "data_dir", "wal"} <= set(entry)
                assert sum(s["objects"] for s in doc["shards"]) == 2
            finally:
                server.shutdown()
        finally:
            plane.close()
