{{- define "cron-operator-tpu.name" -}}
{{ .Chart.Name | trunc 63 | trimSuffix "-" }}
{{- end -}}

{{- define "cron-operator-tpu.fullname" -}}
{{- if eq .Release.Name .Chart.Name -}}
{{ .Release.Name | trunc 63 | trimSuffix "-" }}
{{- else -}}
{{ printf "%s-%s" .Release.Name .Chart.Name | trunc 63 | trimSuffix "-" }}
{{- end -}}
{{- end -}}

{{- define "cron-operator-tpu.serviceAccountName" -}}
{{- if .Values.serviceAccount.name -}}
{{ .Values.serviceAccount.name }}
{{- else -}}
{{ include "cron-operator-tpu.fullname" . }}
{{- end -}}
{{- end -}}

{{- define "cron-operator-tpu.imageTag" -}}
{{ .Values.image.tag | default .Chart.AppVersion }}
{{- end -}}

{{- define "cron-operator-tpu.image" -}}
{{- if .Values.image.registry -}}
{{ .Values.image.registry }}/{{ .Values.image.repository }}:{{ include "cron-operator-tpu.imageTag" . }}
{{- else -}}
{{ .Values.image.repository }}:{{ include "cron-operator-tpu.imageTag" . }}
{{- end -}}
{{- end -}}

{{- define "cron-operator-tpu.labels" -}}
app.kubernetes.io/name: {{ include "cron-operator-tpu.name" . }}
app.kubernetes.io/instance: {{ .Release.Name }}
app.kubernetes.io/version: {{ .Chart.AppVersion | quote }}
app.kubernetes.io/managed-by: Helm
{{- end -}}

{{- define "cron-operator-tpu.selectorLabels" -}}
app.kubernetes.io/name: {{ include "cron-operator-tpu.name" . }}
app.kubernetes.io/instance: {{ .Release.Name }}
{{- end -}}
