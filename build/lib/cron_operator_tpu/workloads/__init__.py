"""Schedulable training workloads.

In the reference's world a workload is a container image the operator never
looks inside (SURVEY.md §3.2 hand-off boundary). In the local TPU runtime a
workload is a registered entrypoint (``backends.registry``) built from the
pieces in this package: a model (:mod:`models`), a sharded train step
(:mod:`workloads.train`), and synthetic data (:mod:`workloads.data`).

Importing this package registers the standard entrypoints
(``mnist`` / ``resnet50`` / ``bert``) used by the BASELINE.md acceptance
configs and by ``bench.py``.
"""

from cron_operator_tpu.workloads.train import (
    TrainConfig,
    Trainer,
    cross_entropy_loss,
)
from cron_operator_tpu.workloads import entrypoints as _entrypoints  # noqa: F401

__all__ = ["TrainConfig", "Trainer", "cross_entropy_loss"]
