"""Injectable clock.

The reference reconciler takes ``now`` explicitly in its schedule math
(``cron_controller.go:184,389``) which is what makes it testable without
sleeping; we push that one step further with a process-wide injectable clock
so the manager loop, executor and tests share one time source.
"""

from __future__ import annotations

import threading
from datetime import datetime, timedelta, timezone


class Clock:
    def now(self) -> datetime:
        raise NotImplementedError

    def sleep(self, seconds: float) -> None:
        raise NotImplementedError


class RealClock(Clock):
    def now(self) -> datetime:
        return datetime.now(timezone.utc)

    def sleep(self, seconds: float) -> None:
        import time

        time.sleep(seconds)


class FakeClock(Clock):
    """Deterministic clock for tests; ``sleep`` advances virtual time."""

    def __init__(self, start: datetime | None = None):
        self._now = start or datetime(2026, 1, 1, tzinfo=timezone.utc)
        self._lock = threading.Lock()

    def now(self) -> datetime:
        with self._lock:
            return self._now

    def sleep(self, seconds: float) -> None:
        self.advance(timedelta(seconds=seconds))

    def advance(self, delta: timedelta) -> datetime:
        with self._lock:
            self._now += delta
            return self._now

    def set(self, t: datetime) -> None:
        with self._lock:
            self._now = t


__all__ = ["Clock", "RealClock", "FakeClock"]
