"""Utilities: clocks, logging, metrics, checkpointing helpers."""

from cron_operator_tpu.utils.clock import Clock, RealClock, FakeClock

__all__ = ["Clock", "RealClock", "FakeClock"]
