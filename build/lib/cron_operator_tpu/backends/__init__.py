"""Workload backends.

The reference hands created workloads to the *external* Kubeflow
training-operator and only watches status conditions come back
(SURVEY.md §3.2 hand-off). This framework ships that half too:

- ``tpu``   — TPU slice topology model (v4/v5e/v5p/v6e shapes, hosts,
              chips-per-host), GKE nodeSelector/resource injection, and JAX
              distributed-coordinator env rendering — the operator-side
              machinery that makes a JAXJob land on a multi-host TPU slice
              as one gang.
- ``local`` — the local training runtime: watches JAXJob-convention
              workloads in the embedded control plane and actually executes
              them in-process on the available TPU/CPU devices, driving the
              Kubeflow JobStatus condition lifecycle
              (Created→Running→Succeeded/Failed) that the reconciler's
              status contract consumes.
- ``registry`` — maps workload entrypoints to Python callables.
"""

from cron_operator_tpu.backends.tpu import (
    SliceSpec,
    TopologyError,
    slice_for,
    inject_tpu_topology,
    render_coordinator_env,
)
from cron_operator_tpu.backends.local import LocalExecutor
from cron_operator_tpu.backends.registry import (
    register_entrypoint,
    resolve_entrypoint,
    JobContext,
)

__all__ = [
    "SliceSpec",
    "TopologyError",
    "slice_for",
    "inject_tpu_topology",
    "render_coordinator_env",
    "LocalExecutor",
    "register_entrypoint",
    "resolve_entrypoint",
    "JobContext",
]
