"""MNIST-scale MLP — the smallest schedulable workload.

Fills the role of the reference's MNIST example images
(``/root/reference/examples/v1alpha1/cron/cron-pytorch.yaml`` runs
``pytorch-dist-mnist``): acceptance configs 1-2 in BASELINE.md schedule this
model on CPU / a single v5e chip.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp


class MLP(nn.Module):
    """Dense → relu stack over flattened images."""

    features: Sequence[int] = (512, 256)
    num_classes: int = 10
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        x = x.reshape(x.shape[0], -1).astype(self.dtype)
        for width in self.features:
            x = nn.Dense(width, dtype=self.dtype)(x)
            x = nn.relu(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)


__all__ = ["MLP"]
