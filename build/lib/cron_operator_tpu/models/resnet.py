"""ResNet (v1.5) — the flagship benchmark model.

The north-star config (BASELINE.md: ResNet-50 JAXJob on a v5e-16 slice,
tick→first-step ≤ 90 s) schedules this model. TPU-first choices:

- bf16 compute / f32 params: convs land on the MXU at full rate;
- GroupNorm instead of BatchNorm: keeps the train step a pure function of
  (params, batch) — no mutable batch statistics to thread through pjit, no
  cross-device stat sync, and XLA fuses it into the conv epilogue (same
  accuracy class for the scheduling benchmarks this framework runs);
- NHWC layout (TPU conv native), strided 1×1 downsampling on the shortcut
  (v1.5 puts the stride on the 3×3 — both shapes tile cleanly).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

Conv = partial(nn.Conv, use_bias=False, dtype=jnp.bfloat16)


class BottleneckBlock(nn.Module):
    """1×1 → 3×3 → 1×1 bottleneck with projection shortcut when shapes change."""

    filters: int
    strides: Tuple[int, int] = (1, 1)
    norm: Callable[..., Any] = nn.GroupNorm
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        residual = x
        y = Conv(self.filters, (1, 1), dtype=self.dtype)(x)
        y = self.norm(dtype=self.dtype)(y)
        y = nn.relu(y)
        # v1.5: stride lives on the 3x3, not the 1x1.
        y = Conv(self.filters, (3, 3), self.strides, dtype=self.dtype)(y)
        y = self.norm(dtype=self.dtype)(y)
        y = nn.relu(y)
        y = Conv(self.filters * 4, (1, 1), dtype=self.dtype)(y)
        y = self.norm(dtype=self.dtype)(y)
        if residual.shape != y.shape:
            residual = Conv(
                self.filters * 4, (1, 1), self.strides, dtype=self.dtype
            )(x)
            residual = self.norm(dtype=self.dtype)(residual)
        return nn.relu(residual + y)


class BasicBlock(nn.Module):
    """Two 3×3 convs (ResNet-18/34)."""

    filters: int
    strides: Tuple[int, int] = (1, 1)
    norm: Callable[..., Any] = nn.GroupNorm
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        residual = x
        y = Conv(self.filters, (3, 3), self.strides, dtype=self.dtype)(x)
        y = self.norm(dtype=self.dtype)(y)
        y = nn.relu(y)
        y = Conv(self.filters, (3, 3), dtype=self.dtype)(y)
        y = self.norm(dtype=self.dtype)(y)
        if residual.shape != y.shape:
            residual = Conv(
                self.filters, (1, 1), self.strides, dtype=self.dtype
            )(x)
            residual = self.norm(dtype=self.dtype)(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    """Stage-configurable ResNet over NHWC images."""

    stage_sizes: Sequence[int]
    block: Any = BottleneckBlock
    num_classes: int = 1000
    width: int = 64
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        x = x.astype(self.dtype)
        x = Conv(self.width, (7, 7), (2, 2), padding=[(3, 3), (3, 3)],
                 dtype=self.dtype)(x)
        x = nn.GroupNorm(dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        for stage, n_blocks in enumerate(self.stage_sizes):
            for b in range(n_blocks):
                strides = (2, 2) if stage > 0 and b == 0 else (1, 1)
                x = self.block(
                    filters=self.width * (2 ** stage),
                    strides=strides,
                    dtype=self.dtype,
                )(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)


ResNet18 = partial(ResNet, stage_sizes=(2, 2, 2, 2), block=BasicBlock)
ResNet50 = partial(ResNet, stage_sizes=(3, 4, 6, 3), block=BottleneckBlock)

__all__ = ["ResNet", "ResNet18", "ResNet50", "BottleneckBlock", "BasicBlock"]
