"""cron_operator_tpu — a TPU-native cron-scheduling framework for ML training.

A from-scratch rebuild of the capability set of
``AliyunContainerService/cron-operator`` (a Kubernetes operator that launches
Kubeflow training jobs on cron schedules), redesigned TPU-first:

- ``api``        — the ``Cron`` resource model (group ``apps.kubedl.io/v1alpha1``)
                   and the Kubeflow-compatible JobStatus condition contract.
- ``controller`` — the reconciler (concurrency policies, missed-run catch-up,
                   history retention/GC) and the cron schedule engine.
- ``runtime``    — the embedded control-plane runtime: an in-memory
                   Kubernetes-style object store with watches, owner-reference
                   garbage collection and events, plus the manager that wires
                   controllers to it.
- ``backends``   — workload backends. Unlike the reference (which hands
                   workloads to an external training-operator), this framework
                   ships a local training runtime that executes JAXJobs
                   in-process on TPU, plus TPU slice topology modeling
                   (v5e/v5p shapes, gang semantics, preemption).
- ``models``     — flagship JAX/Flax training workloads (MNIST, ResNet-50,
                   BERT) used by examples, benchmarks and tests.
- ``parallel``   — device-mesh construction and sharding strategies
                   (DP/FSDP/TP/SP) over ``jax.sharding`` + ``shard_map``.
- ``ops``        — Pallas TPU kernels and fused ops (ring attention, ...).
- ``utils``      — logging, metrics, checkpointing helpers.

Reference parity map lives in SURVEY.md; citations in docstrings point at
``/root/reference`` file:line.
"""

__version__ = "0.1.0"
