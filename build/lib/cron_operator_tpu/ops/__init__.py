"""Hot-op kernel layer: Pallas TPU kernels with XLA fallbacks.

The compute path of the workloads this framework schedules. Attention is the
one op worth hand-scheduling on TPU (everything else — convs, matmuls,
norms — XLA already tiles onto the MXU and fuses well); the flash kernel
keeps the S×S score matrix out of HBM entirely.
"""

from cron_operator_tpu.ops.attention import multi_head_attention, reference_attention
from cron_operator_tpu.ops.flash_attention import flash_attention

__all__ = ["multi_head_attention", "reference_attention", "flash_attention"]
