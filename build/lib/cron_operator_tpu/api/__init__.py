"""API layer: resource types, group/version registration, status contract.

Mirrors the reference's ``api/v1alpha1`` package
(``/root/reference/api/v1alpha1/cron_types.go``) in capability, re-expressed
as Python dataclasses over k8s-style unstructured dicts.
"""

from cron_operator_tpu.api.v1alpha1 import (
    GROUP,
    VERSION,
    API_VERSION,
    KIND_CRON,
    ConcurrencyPolicy,
    JobConditionType,
    JobCondition,
    JobStatus,
    ObjectMeta,
    ObjectReference,
    TypedLocalObjectReference,
    CronHistory,
    CronTemplateSpec,
    CronSpec,
    CronStatus,
    Cron,
)
from cron_operator_tpu.api.scheme import Scheme, default_scheme

__all__ = [
    "GROUP",
    "VERSION",
    "API_VERSION",
    "KIND_CRON",
    "ConcurrencyPolicy",
    "JobConditionType",
    "JobCondition",
    "JobStatus",
    "ObjectMeta",
    "ObjectReference",
    "TypedLocalObjectReference",
    "CronHistory",
    "CronTemplateSpec",
    "CronSpec",
    "CronStatus",
    "Cron",
    "Scheme",
    "default_scheme",
]
