"""End-to-end benchmark: cron-tick → first-train-step latency.

The BASELINE.md north-star metric: a Cron fires, the reconciler instantiates
a JAXJob, the local TPU runtime admits it (topology injection), and the
ResNet-50 workload reaches its first *completed* optimizer step on the
device. Target ≤ 90 s (BASELINE.json; the reference publishes no numbers of
its own — BASELINE.md "Reference-published benchmarks: None").

Hardening after the round-1 null result (VERDICT.md weak #1):

- **Bounded device probe.** The tunneled TPU backend's client init can hang
  indefinitely (observed: >14 min at 0% CPU). The probe runs in a
  subprocess with a deadline; if the TPU is unreachable the bench falls
  back to CPU and says so in ``extra.platform`` / ``extra.tpu_probe`` —
  a labeled number beats a null.
- **Subprocess workloads.** Jobs execute via ``workloads.runner`` child
  processes (LocalExecutor ``isolation="subprocess"``), so a timeout is a
  clean SIGTERM/SIGKILL of the child — the round-1 failure mode (killing a
  thread mid-XLA-compile wedged the chip for every later run) cannot recur.
- **Compile pre-warm + persistent cache.** The entrypoint is run once
  before the Cron is created (same shapes, persistent XLA compile cache on
  disk), so the measured tick→first-step latency is scheduling + dispatch +
  cache-hit compile — the thing the 90 s target is about — not cold-compile
  of an experimental platform.
- **Failure diagnostics.** On timeout or job failure the JSON carries the
  job's conditions, events, and the runner's stderr tail (folded into the
  Failed condition message by the executor), never a bare null.

Prints ONE JSON line:
  {"metric": "tick_to_first_train_step_s", "value": ..., "unit": "s",
   "vs_baseline": <90/value>, "extra": {...}}
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

BASELINE_TARGET_S = 90.0  # BASELINE.json north star
STEPS = int(os.environ.get("BENCH_STEPS", "40"))
# Fetching the loss is a host↔device round trip (~80-220 ms through the
# tunnel vs a ~55 ms compute step at batch 128). Defaulting sync_every to
# the step count makes the Trainer sync only the FIRST step (the
# tick→first-step anchor must be device-completed) and the LAST (drain),
# so exactly one RTT amortizes over the whole steady-state tail instead
# of one per 10 steps — the r5 interim artifact measured 98 ms/step with
# sync_every=10 vs 53 ms pure-device time (hack/mfu_probe.py chain) for
# the identical program; the difference was all link, no device.
SYNC_EVERY = int(os.environ.get("BENCH_SYNC_EVERY", str(STEPS)))
# 128, not 64: the r5 sweep (hack/mfu_probe.py, TPU-measured) put the
# chain-timed step at 2034 img/s @64 vs 2408 img/s @128 (flat again at
# 256) — 64 under-feeds the MXU.
BATCH = int(os.environ.get("BENCH_BATCH", "128"))
IMAGE = int(os.environ.get("BENCH_IMAGE", "224"))
# CPU-fallback shape: the metric is tick→first-step *latency*
# (scheduling + dispatch + warm compile). At the flagship 224²×64 shape a
# CPU step is pure conv-throughput grind (~90 s/step measured) that says
# nothing about the control plane, so the fallback shrinks the workload
# and labels it in extras. The TPU path always runs the flagship shape.
CPU_BATCH = int(os.environ.get("BENCH_CPU_BATCH", "8"))
CPU_IMAGE = int(os.environ.get("BENCH_CPU_IMAGE", "128"))
# Few enough steps that the CPU-fallback job COMPLETES inside the
# measure window (r5 rehearsal: 40 CPU steps overran the 240 s grace and
# the artifact lost steps_per_s/avg_step_time).
CPU_STEPS = int(os.environ.get("BENCH_CPU_STEPS", "6"))
# Optimizer steps per dispatched program (TrainConfig.steps_per_call):
# amortizes the tunnel's per-dispatch cost, whose drift was the residual
# variable in full-stack runs (PERF.md finding 5). 5 ≈ 265 ms/dispatch
# at the flagship shape — long enough to amortize, short enough that the
# first-call (= tick→first-step anchor) stays sub-second.
STEPS_PER_CALL = int(os.environ.get("BENCH_STEPS_PER_CALL", "5"))
# Round-4 probe strategy (VERDICT r3 #1): ONE long attempt instead of
# r3's 2x150 s that both failed — a tunnel init that hasn't come up in
# 150 s was observed (r4, faulthandler) still inside PJRT client
# creation at 590 s, so retrying short attempts only spends the budget
# twice on the same hang.
PROBE_TIMEOUT_S = float(os.environ.get("BENCH_PROBE_TIMEOUT", "500"))
PROBE_ATTEMPTS = int(os.environ.get("BENCH_PROBE_ATTEMPTS", "1"))
PROBE_BACKOFF_S = float(os.environ.get("BENCH_PROBE_BACKOFF", "15"))
# The axon tunnel's claim leg dials this loopback relay port; a closed
# port means the tunnel infrastructure itself is down and no amount of
# probe budget will bring a device up.
RELAY_PROBE_ADDR = ("127.0.0.1", 8082)
PREWARM_TIMEOUT_S = float(os.environ.get("BENCH_PREWARM_TIMEOUT", "600"))
MEASURE_TIMEOUT_S = float(os.environ.get("BENCH_MEASURE_TIMEOUT", "240"))

# ResNet-50 fwd ≈ 4.1 G multiply-adds @224² = 8.2 GFLOP (a MAC is two
# flops — the classic "4.1 GFLOPs" figure counts MACs; XLA's own cost
# analysis counts 8.03 GFLOP for our fwd, hack/mfu_attrib.py, and the r4
# artifact's mfu used the MAC figure, understating true MFU 2×).
# Backward ≈ 2× fwd. This analytic constant is only the FALLBACK MFU
# numerator — the measured run prefers the compiled step's own
# cost-analysis flops (progress.xla_flops_per_step).
RESNET50_TRAIN_FLOPS_224 = 3 * 2 * 4.1e9
PEAK_FLOPS = (  # (substring of device_kind.lower(), per-chip bf16 peak)
    # Ordered: "lite" variants must match before their bare-version parent
    # — jax reports v5e as "TPU v5 lite" (the r3 dict keyed on the
    # marketing name "v5e" and produced mfu:null on the real chip).
    ("v6 lite", 918e12), ("v6e", 918e12),
    ("v5 lite", 197e12), ("v5e", 197e12),
    ("v5p", 459e12), ("v5", 459e12),
    ("v4", 275e12),
)
PEAK_HBM = (  # (same matching rule, per-chip HBM bytes/s) — the decode
    # roofline denominator (decode is bandwidth-bound, not flops-bound)
    ("v6 lite", 1640e9), ("v6e", 1640e9),
    ("v5 lite", 819e9), ("v5e", 819e9),
    ("v5p", 2765e9), ("v5", 2765e9),
    ("v4", 1228e9),
)


def _flops_per_image(image: int) -> float:
    return RESNET50_TRAIN_FLOPS_224 * (image / 224.0) ** 2


def _relay_preflight() -> dict:
    """Cheap (<1 s) TCP check of the tunnel relay's claim port.

    Distinguishes "tunnel infrastructure down" (nothing listening — no
    probe budget can help) from "relay up but client init hangs" (the
    r1-r4 failure mode; the long probe below captures *where* via
    faulthandler)."""
    import socket

    try:
        with socket.create_connection(RELAY_PROBE_ADDR, timeout=1.0):
            return {"listening": True, "addr": "%s:%d" % RELAY_PROBE_ADDR}
    except OSError as exc:
        return {
            "listening": False,
            "addr": "%s:%d" % RELAY_PROBE_ADDR,
            "error": str(exc),
        }


def _last_stack_dump(stderr: str) -> str:
    """The final faulthandler traceback block in a probe child's stderr —
    the frame the init was blocked in when the deadline hit."""
    marker = "Timeout ("
    idx = stderr.rfind(marker)
    return stderr[idx:][:1500] if idx >= 0 else ""


def _probe_devices(timeout: float, attempts: int = PROBE_ATTEMPTS):
    """Ask a child process what accelerator is actually reachable.

    Returns (platform_arg, info dict). ``platform_arg`` is None for the
    default (TPU) platform or "cpu" for the fallback.

    The child is ``hack/tpu_probe.py``: it arms
    ``faulthandler.dump_traceback_later`` so a hang dumps the blocking
    frame to stderr every 60 s — on timeout the artifact carries the
    hanging stack (``hang_stack``), not silence (VERDICT r3 #1: "a TPU
    number or a stack-dump of exactly where init dies"). Observed r4
    diagnosis: the hang sits in ``jaxlib xla_client make_c_api_client``
    (native PJRT_Client_Create dialing the tunnel relay) — infra, not
    framework; ``relay`` records whether the tunnel's claim port was
    even listening.
    """
    probe_script = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "hack", "tpu_probe.py"
    )
    relay = _relay_preflight()
    if not relay["listening"]:
        # Nothing on the relay's claim port → almost certainly no path to
        # a device. Keep ONE short attempt rather than skipping outright
        # (the port number is a heuristic; 60 s buys the counter-evidence
        # if it's wrong) instead of burning the full long-probe budget.
        timeout = min(60.0, timeout)
    history = []
    for attempt in range(1, attempts + 1):
        t0 = time.time()
        child = subprocess.Popen(
            [sys.executable, probe_script],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        try:
            out, err = child.communicate(timeout=timeout)
            rc = child.returncode
        except subprocess.TimeoutExpired:
            child.kill()
            out, err = child.communicate()
            dump = _last_stack_dump(err or "")
            history.append({
                "attempt": attempt,
                "elapsed_s": round(time.time() - t0, 1),
                "error": f"device init exceeded {timeout:.0f}s (tunnel hang)",
                "hang_stack": dump,
                # Most-recent stderr is the evidence when the child died
                # before faulthandler's first 60 s dump.
                "stderr_tail": "" if dump else (err or "")[-300:],
            })
            if attempt < attempts:
                time.sleep(PROBE_BACKOFF_S)
            continue
        if rc != 0:
            history.append({
                "attempt": attempt,
                "elapsed_s": round(time.time() - t0, 1),
                "error": f"device probe rc={rc}",
                "stderr_tail": (err or "").strip()[-500:],
            })
            # A non-zero exit is deterministic (import/plugin failure), not
            # a tunnel hang — retrying would fail identically; fall back now.
            break
        info = json.loads(out.strip().splitlines()[-1])
        info["ok"] = True
        info["relay"] = relay
        info["init_s"] = round(time.time() - t0, 1)
        info["attempts"] = history + [
            {"attempt": attempt, "elapsed_s": info["init_s"], "ok": True}
        ]
        if info.get("cleared_jax_platforms"):
            # The probe self-healed a stale JAX_PLATFORMS pin (it named a
            # platform no installed plugin registers). Every later child
            # (prewarm, runners, microbench, sweep) inherits our env and
            # would fail identically — clear the pin here too.
            os.environ.pop("JAX_PLATFORMS", None)
        return ("cpu" if info["backend"] == "cpu" else None), info
    return "cpu", {
        "ok": False,
        "error": f"device init failed in {attempts} attempt(s); "
                 "falling back to cpu",
        "relay": relay,
        "attempts": history,
    }


def _prewarm(platform, batch: int, image: int, steps: int, timeout: float):
    """Compile-warm the exact bench computation via the runner subprocess
    (persistent cache makes the measured run a cache hit).

    One prewarm run per distinct program the measured run will dispatch:
    the full steps_per_call scan, plus the remainder-length scan when
    ``steps`` is not a multiple (otherwise that partial-chunk program
    compiles mid-measure and pollutes the steady state)."""
    # CPU fallback keeps one step per dispatch: there is no link to
    # amortize, and a multi-step first call would inflate its
    # tick->first-step anchor by whole CPU-step durations. max(1, ...):
    # BENCH_STEPS_PER_CALL=0 means "disable", not ZeroDivisionError.
    spc = max(1, STEPS_PER_CALL) if platform is None else 1
    # min(spc, steps): when steps < spc the measured run's only chunk IS
    # the remainder — don't burn prewarm budget on an unused program.
    lengths = [min(spc, steps)]
    if steps % spc and steps > spc:
        lengths.append(steps % spc)
    t0 = time.time()
    for length in lengths:
        args = [
            sys.executable, "-m", "cron_operator_tpu.workloads.runner",
            "resnet50", f"steps={length}",
            f"batch_size={batch}", f"image_size={image}",
            # Must match the measured run's programs exactly: fused data
            # AND the scan-of-length program.
            "data=fused", f"steps_per_call={length}",
            # Prewarm ALSO populates the persistent cache for the
            # measured run's post-run flops cost-analysis (a re-lower +
            # re-compile of the single-step program).
            "flops_accounting=1",
        ]
        if platform:
            args.append(f"platform={platform}")
        remaining = timeout - (time.time() - t0)
        try:
            out = subprocess.run(args, capture_output=True, text=True,
                                 timeout=max(1.0, remaining))
        except subprocess.TimeoutExpired:
            return {"ok": False,
                    "error": f"prewarm exceeded {timeout:.0f}s"}
        if out.returncode != 0:
            return {
                "ok": False,
                "error": f"prewarm rc={out.returncode}: "
                         f"{(out.stderr or '').strip()[-800:]}",
            }
    return {"ok": True, "seconds": round(time.time() - t0, 1),
            "programs": lengths}


def _attention_microbench(platform, timeout: float):
    """flash-vs-xla attention timing on the reachable device (subprocess,
    bounded). On TPU this is the Mosaic-compile + correctness + perf
    evidence for the Pallas kernel; skipped on the CPU fallback (interpret
    timings are meaningless)."""
    if platform == "cpu":
        return {"skipped": "cpu fallback (interpret mode is not a perf path)"}
    # seq 2048: the shape where the flash kernel's reason-to-exist lives
    # (auto-dispatch only picks it from seq ≥1024; at 512 dense XLA wins).
    args = [sys.executable, "-m", "cron_operator_tpu.ops.microbench",
            "seq=2048", "batch=4", "heads=8", "head_dim=64", "iters=20"]
    try:
        out = subprocess.run(args, capture_output=True, text=True,
                             timeout=timeout)
    except subprocess.TimeoutExpired:
        return {"error": f"microbench exceeded {timeout:.0f}s"}
    if out.returncode != 0:
        return {"error": f"rc={out.returncode}: "
                         f"{(out.stderr or '').strip()[-400:]}"}
    try:
        return json.loads(out.stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        return {"error": f"unparseable output: {out.stdout[-200:]}"}


def _runner_progress(runner_args, timeout: float):
    """Run a workloads.runner subprocess → ``(progress, error)`` tuple:
    exactly one side is non-None. Never raises — bench legs must not
    poison the headline metric."""
    args = [sys.executable, "-m", "cron_operator_tpu.workloads.runner",
            *runner_args]
    try:
        out = subprocess.run(args, capture_output=True, text=True,
                             timeout=timeout)
    except subprocess.TimeoutExpired:
        return None, {"error": f"exceeded {timeout:.0f}s"}
    if out.returncode != 0:
        return None, {"error": f"rc={out.returncode}: "
                               f"{(out.stderr or '').strip()[-400:]}"}
    from cron_operator_tpu.workloads.runner import PROGRESS_PREFIX

    progress = {}
    for line in out.stdout.splitlines():
        if line.startswith(PROGRESS_PREFIX):
            try:
                msg = json.loads(line[len(PROGRESS_PREFIX):])
            except ValueError:
                continue
            progress = msg.get("progress") or progress
    if not progress:
        return None, {"error": f"no progress parsed: {out.stdout[-200:]}"}
    return progress, None


def _lm_bench(platform, timeout: float) -> dict:
    """BERT-base seq-512 steady-state throughput via the runner subprocess
    — the language-model leg of the BASELINE configs (the tick→first-step
    headline uses ResNet-50; this evidences the transformer/attention
    path end-to-end on the same device). Skipped on the CPU fallback."""
    if platform == "cpu":
        return {"skipped": "cpu fallback"}
    progress, err = _runner_progress(
        ["bert", "steps=24", "batch_size=8", "seq_len=512",
         # first+last sync only (see SYNC_EVERY above) + in-step data
         # generation + 6 steps per dispatch: the steady state is four
         # dispatches total.
         "sync_every=24", "data=fused", "steps_per_call=6",
         "flops_accounting=1"],
        timeout,
    )
    if err:
        return err
    if not progress.get("steps_per_s"):
        return {"error": f"no steady-state progress: {progress}"}
    return {
        "model": "bert-base", "batch_size": 8, "seq_len": 512,
        "steps_per_s": progress["steps_per_s"],
        "avg_step_time_s": progress.get("avg_step_time_s"),
        "tokens_per_s": round(8 * 512 * progress["steps_per_s"], 1),
        "last_loss": progress.get("last_loss"),
    }


def _decode_bench(platform, device_kind: str, timeout: float) -> dict:
    """GPT-base KV-cache decode throughput via the `generate` entrypoint
    (serving path: batched prefill + lax.scan sampling), swept over batch
    — THE decode throughput lever — and placed against the chip's HBM
    roofline (VERDICT r4 #6: "possibly fine, possibly 3× headroom, the
    artifact can't say").

    The roofline model: each decode step reads the bf16 params once for
    the whole batch plus every item's full static KV cache (the
    entrypoint publishes the byte count, see
    entrypoints.generate_job); perfect bandwidth-bound decode would run
    batch × HBM_bytes_per_s / read_bytes_per_step tokens/s.
    """
    if platform == "cpu":
        return {"skipped": "cpu fallback"}
    hbm = next(
        (v for k, v in PEAK_HBM if k in (device_kind or "").lower()), None
    )
    deadline = time.time() + timeout  # TOTAL for the whole sweep
    sweep = []
    for batch in (8, 16, 32):
        remaining = deadline - time.time()
        if remaining < 30.0:
            sweep.append({"batch_size": batch,
                          "skipped": "decode budget exhausted"})
            continue
        progress, err = _runner_progress(
            ["generate", "rounds=3", f"batch_size={batch}",
             "prompt_len=64", "max_new=128"],
            min(300.0, remaining),
        )
        if err:
            sweep.append({"batch_size": batch, **err})
            continue
        if not progress.get("tokens_per_s"):
            sweep.append({"batch_size": batch,
                          "error": f"no steady throughput: {progress}"})
            continue
        leg = {
            "batch_size": batch,
            "decode_tokens_per_s": progress["tokens_per_s"],
            "read_bytes_per_step": progress.get(
                "decode_read_bytes_per_step"
            ),
        }
        if hbm and leg["read_bytes_per_step"]:
            roof = batch * hbm / leg["read_bytes_per_step"]
            leg["hbm_roofline_tokens_per_s"] = round(roof, 1)
            leg["pct_of_hbm_roofline"] = round(
                100.0 * progress["tokens_per_s"] / roof, 1
            )
        sweep.append(leg)
    out = {
        "model": "gpt-base", "prompt_len": 64, "max_new": 128,
        "read_bytes_model": (
            "bf16 params (scan-hoisted cast, read once per step) + full "
            "static KV cache per step; entrypoints.generate_job"
        ),
        "hbm_bytes_per_s": hbm,
        "sweep": sweep,
    }
    # Headline continuity with r1-r4 artifacts: the batch-8 number.
    first = next((s for s in sweep if s.get("decode_tokens_per_s")), None)
    if first:
        out["batch_size"] = first["batch_size"]
        out["decode_tokens_per_s"] = first["decode_tokens_per_s"]
    return out


def _mfu_sweep(platform, timeout: float) -> dict:
    """Batch sweep + dispatch-vs-chain attribution for the flagship
    (VERDICT r4 #1: "bench.py:413 hardcodes batch 64 with no sweep ...
    no attribution"). Runs hack/mfu_probe.py — chain mode times a
    compiled scan of train steps (pure device compute, span-differenced),
    dispatch mode times the Trainer's one-call-per-step shape; MFU uses
    the same 2×MAC flops model as the analytic fallback here. Bounded
    and fail-soft: the headline metric never depends on it."""
    if platform == "cpu":
        return {"skipped": "cpu fallback"}
    probe = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "hack", "mfu_probe.py"
    )
    try:
        out = subprocess.run(
            [sys.executable, probe, "batch=64,128,256", "chain=5"],
            capture_output=True, text=True, timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        return {"error": f"sweep exceeded {timeout:.0f}s"}
    if out.returncode != 0:
        return {"error": f"rc={out.returncode}: "
                         f"{(out.stderr or '').strip()[-400:]}"}
    try:
        return json.loads(out.stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        return {"error": f"unparseable output: {out.stdout[-200:]}"}


def _control_plane_bench(n_crons: int = 300) -> dict:
    """Scheduling-throughput microbench — no device involved.

    The reference's operating envelope is 10 concurrent reconciles at
    client QPS 30 (BASELINE.md table); this measures what OUR control
    plane sustains: N due crons reconciled to workload creation (the full
    hot loop: list, status sync, schedule math, TPU admission, create),
    then the steady-state pass where no tick is due. FakeClock makes the
    tick instant deterministic.
    """
    from cron_operator_tpu.controller import CronReconciler
    from cron_operator_tpu.runtime import APIServer
    from cron_operator_tpu.runtime.manager import Metrics
    from cron_operator_tpu.utils.clock import FakeClock
    from datetime import timedelta

    clock = FakeClock()
    api = APIServer(clock=clock)
    rec = CronReconciler(api, metrics=Metrics())
    for i in range(n_crons):
        api.create({
            "apiVersion": "apps.kubedl.io/v1alpha1", "kind": "Cron",
            "metadata": {"name": f"cp-{i}", "namespace": "default"},
            "spec": {
                "schedule": "@every 60s",
                "concurrencyPolicy": "Forbid",
                "template": {"workload": {
                    "apiVersion": "kubeflow.org/v1", "kind": "JAXJob",
                    "metadata": {"annotations": {
                        "tpu.kubedl.io/accelerator": "v5e",
                        "tpu.kubedl.io/topology": "2x2",
                    }},
                    "spec": {"replicaSpecs": {"Worker": {"replicas": 1}}},
                }},
            },
        })
    clock.advance(timedelta(seconds=61))  # every cron now has a due tick

    t0 = time.perf_counter()
    for i in range(n_crons):
        rec.reconcile("default", f"cp-{i}")
    fire_dt = time.perf_counter() - t0
    created = len(api.list("kubeflow.org/v1", "JAXJob",
                           namespace="default"))

    t0 = time.perf_counter()
    for i in range(n_crons):
        rec.reconcile("default", f"cp-{i}")  # no tick due; Forbid+active
    idle_dt = time.perf_counter() - t0
    api.close()

    return {
        "n_crons": n_crons,
        "workloads_created": created,
        "fire_reconciles_per_s": round(n_crons / fire_dt, 1),
        "steady_reconciles_per_s": round(n_crons / idle_dt, 1),
        "reference_envelope": "10 concurrent reconciles @ client QPS 30",
    }


def _emit(value, extra, error=None) -> int:
    rec = {
        "metric": "tick_to_first_train_step_s",
        "value": value,
        "unit": "s",
        "vs_baseline": (
            round(BASELINE_TARGET_S / value, 3) if value else 0.0
        ),
        "extra": extra,
    }
    if error:
        rec["error"] = error
    print(json.dumps(rec))
    return 0 if value is not None else 1


def main() -> int:
    # Persistent compile cache for every child (prewarm → measured run).
    os.environ.setdefault(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     ".jax_cache"),
    )
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")

    # Global wall-clock budget: with a SICK-but-up tunnel every leg can
    # run to its own timeout and the worst case reaches hours — and an
    # external kill loses the whole artifact, since the JSON only prints
    # at the end. Optional legs get min(their timeout, what is left
    # after reserving room for the measured run); when nothing is left
    # they are skipped with a label instead of silently starving the
    # headline.
    t_begin = time.time()
    total_budget = float(os.environ.get("BENCH_TOTAL_BUDGET", "2700"))
    reserve = MEASURE_TIMEOUT_S * 2 + 60.0  # measured run + grace + emit

    def leg_timeout(want: float) -> float:
        remaining = total_budget - (time.time() - t_begin) - reserve
        return min(want, max(0.0, remaining))

    platform, probe = _probe_devices(PROBE_TIMEOUT_S)

    def shape_for(platform):
        return (
            (BATCH, IMAGE, STEPS) if platform is None
            else (CPU_BATCH, CPU_IMAGE, CPU_STEPS)
        )

    batch, image, steps = shape_for(platform)
    extra = {
        "model": "resnet50", "batch_size": batch, "image_size": image,
        "steps": steps, "baseline_target_s": BASELINE_TARGET_S,
        "tpu_probe": probe,
        "platform": probe.get("backend", "cpu") if probe.get("ok") else "cpu",
    }
    if platform == "cpu":
        extra["cpu_fallback_shape"] = (
            f"shrunk from {BATCH}x{IMAGE} (flagship) to keep the metric "
            "about scheduling latency, not CPU conv throughput"
        )
        extra["cpu_trend_note"] = (
            "CPU numbers vary run-to-run with shared-host load (r2 16.7s "
            "→ r3 20.6s on identical config; prewarm moved 15.4→21.2s in "
            "step — machine noise, not a control-plane change). The CPU "
            "figure evidences the control plane end-to-end, not steady "
            "throughput."
        )

    warm = _prewarm(platform, batch, image, steps, PREWARM_TIMEOUT_S)
    if not warm.get("ok") and platform is None:
        # TPU path compiled/ran sick — retry the whole bench on CPU rather
        # than returning nothing.
        extra["tpu_prewarm_error"] = warm.get("error")
        platform = "cpu"
        batch, image, steps = shape_for(platform)
        extra.update(platform="cpu", batch_size=batch, image_size=image,
                     steps=steps)
        warm = _prewarm(platform, batch, image, steps, PREWARM_TIMEOUT_S)
    extra["prewarm"] = warm
    if not warm.get("ok"):
        return _emit(None, extra, error=f"prewarm failed: {warm.get('error')}")

    def run_leg(name, fn, want):
        t = leg_timeout(want)
        if t < 30.0:
            extra[name] = {"skipped": "global budget exhausted "
                                      "(BENCH_TOTAL_BUDGET)"}
            return
        extra[name] = fn(t)

    run_leg("attention_bench",
            lambda t: _attention_microbench(platform, timeout=t), 300.0)
    run_leg("lm_bench", lambda t: _lm_bench(platform, timeout=t), 240.0)
    run_leg(
        "decode_bench",
        lambda t: _decode_bench(platform, probe.get("kind") or "",
                                timeout=t),
        600.0,  # split across the three batch legs inside
    )
    try:
        extra["control_plane"] = _control_plane_bench()
    except Exception as exc:  # noqa: BLE001 — a microbench must not
        # poison the headline metric
        extra["control_plane"] = {"error": str(exc)}

    # ---- the measured run: full stack, subprocess isolation ---------------
    from cron_operator_tpu.api.scheme import GVK_CRON, default_scheme
    from cron_operator_tpu.backends.local import LocalExecutor
    from cron_operator_tpu.controller import CronReconciler
    from cron_operator_tpu.runtime import APIServer, Manager

    api = APIServer()
    scheme = default_scheme()
    # 2 workers, not the reference envelope's 10: the measured run has
    # ONE cron, and on a small shared host every idle operator thread
    # steals cycles from the training child's dispatch thread (the
    # control-plane throughput envelope is measured separately above).
    manager = Manager(api, max_concurrent_reconciles=2)
    reconciler = CronReconciler(api, metrics=manager.metrics)
    manager.add_controller(
        "cron", reconciler.reconcile, for_gvk=GVK_CRON,
        owns=scheme.workload_kinds(),
    )
    executor = LocalExecutor(api, isolation="subprocess")

    annotations = {
        "tpu.kubedl.io/entrypoint": "resnet50",
        "tpu.kubedl.io/param.steps": str(steps),
        "tpu.kubedl.io/param.batch_size": str(batch),
        "tpu.kubedl.io/param.image_size": str(image),
        # sync first + last only when defaulted (see SYNC_EVERY above).
        "tpu.kubedl.io/param.sync_every": str(min(SYNC_EVERY, steps)),
        # Fused in-step data generation: the steady state is one dispatch
        # per step, nothing per-step on the host (PERF.md finding 3-4).
        "tpu.kubedl.io/param.data": "fused",
        "tpu.kubedl.io/param.steps_per_call": str(
            max(1, STEPS_PER_CALL) if platform is None else 1
        ),
        "tpu.kubedl.io/param.flops_accounting": "1",
        # Belt & braces: never let one tick run unbounded.
        "tpu.kubedl.io/job-timeout": f"{int(MEASURE_TIMEOUT_S)}s",
    }
    if platform:
        annotations["tpu.kubedl.io/param.platform"] = platform
    cron = {
        "apiVersion": "apps.kubedl.io/v1alpha1",
        "kind": "Cron",
        "metadata": {"name": "bench-resnet50", "namespace": "default"},
        "spec": {
            "schedule": "@every 5s",
            "concurrencyPolicy": "Forbid",
            "historyLimit": 3,
            "template": {
                "workload": {
                    "apiVersion": "kubeflow.org/v1",
                    "kind": "JAXJob",
                    "metadata": {"annotations": annotations},
                    "spec": {"replicaSpecs": {"Worker": {"replicas": 1}}},
                }
            },
        },
    }

    executor.start()
    manager.start()
    api.create(cron)

    deadline = time.time() + MEASURE_TIMEOUT_S
    job = None
    progress = {}
    failures = []
    try:
        while time.time() < deadline:
            jobs = api.list("kubeflow.org/v1", "JAXJob", namespace="default")
            for j in jobs:
                p = (j.get("status") or {}).get("trainingProgress") or {}
                if p.get("first_step_at"):
                    job, progress = j, p
                    break
                conds = (j.get("status") or {}).get("conditions") or []
                for c in conds:
                    if c["type"] == "Failed":
                        failures.append({
                            "job": j["metadata"]["name"],
                            "message": c.get("message", "")[-1200:],
                        })
            if job is not None or failures:
                break
            time.sleep(1.0)  # coarse: the parent must stay quiet while
            # the training child owns the core (PERF.md finding 3)
        if job is not None:
            # Let the run finish cleanly (steady-state steps → steps_per_s;
            # never SIGKILL a live device program — chip hygiene).
            name = job["metadata"]["name"]
            grace = time.time() + MEASURE_TIMEOUT_S
            while time.time() < grace:
                j = api.try_get("kubeflow.org/v1", "JAXJob", "default", name)
                if j is None:
                    break
                st = j.get("status") or {}
                progress = st.get("trainingProgress") or progress
                if any(
                    c["type"] in ("Succeeded", "Failed")
                    for c in st.get("conditions") or []
                ):
                    break
                time.sleep(1.0)
    finally:
        manager.stop()
        executor.stop()
        api.close()

    if job is None:
        # Diagnostics: conditions + events of every job seen, so the
        # artifact explains itself.
        diag = {"failures": failures, "jobs": []}
        for j in api.list("kubeflow.org/v1", "JAXJob", namespace="default"):
            st = j.get("status") or {}
            diag["jobs"].append({
                "name": j["metadata"]["name"],
                "conditions": [
                    {k: c.get(k) for k in ("type", "reason", "message")}
                    for c in st.get("conditions") or []
                ],
                "trainingProgress": st.get("trainingProgress"),
            })
        diag["events"] = [
            f"{e.reason}: {e.message}" for e in api.events()
        ][-10:]
        extra["diagnostics"] = diag
        why = (
            f"job failed: {failures[0]['message']}" if failures
            else f"no job reached its first train step within "
                 f"{MEASURE_TIMEOUT_S:.0f}s"
        )
        return _emit(None, extra, error=why)

    # Tick anchor: the workload's creationTimestamp. The reconcile that
    # creates it runs on the RequeueAfter timer at the activation instant,
    # so creation time ≈ tick time (the job NAME encodes next_run — one
    # interval in the future, a reference-parity quirk — so it is not a
    # usable anchor). RFC3339 gives whole-second precision; good enough
    # against a 90 s target.
    from cron_operator_tpu.api.v1alpha1 import parse_time

    created = parse_time(job["metadata"]["creationTimestamp"])
    latency = progress["first_step_at"] - created.timestamp()

    steps_per_s = progress.get("steps_per_s")
    images_per_s = (
        round(batch * steps_per_s, 2) if steps_per_s else None
    )
    kind = (probe.get("kind") or "").lower()
    peak = next(
        (v for k, v in PEAK_FLOPS if k in kind), None
    )
    # images_per_s is whole-job throughput across the mesh; peak is
    # per-chip, so scale by device count or multi-chip MFU inflates by
    # n_devices× (ADVICE r2).
    n_chips = probe.get("n") or 1
    # MFU numerator: prefer XLA's cost analysis of the ACTUAL compiled
    # step (published by the entrypoint, Trainer.flops_per_step) over the
    # analytic table — the model the chip runs, not the model on paper.
    # cost_analysis reports the PER-DEVICE post-GSPMD-partitioning module,
    # so per-device flops × steps/s against the PER-CHIP peak is per-chip
    # utilization for any n_chips (dividing by n_chips here would
    # understate multi-chip MFU n×; the analytic branch's numerator is
    # global, so IT scales by n_chips).
    xla_flops = progress.get("xla_flops_per_step")
    if steps_per_s and peak and xla_flops:
        mfu = round(xla_flops * steps_per_s / peak, 4)
        mfu_source = "xla_cost_analysis"
    elif images_per_s and peak:
        mfu = round(
            images_per_s * _flops_per_image(image) / (peak * n_chips), 4
        )
        mfu_source = "analytic_2x_mac"
    else:
        mfu, mfu_source = None, None
    extra.update({
        "n_devices": probe.get("n"),
        "device_kind": probe.get("kind"),
        "steps_per_s": steps_per_s,
        "avg_step_time_s": progress.get("avg_step_time_s"),
        "images_per_s": images_per_s,
        "model_flops_per_image": _flops_per_image(image),
        "xla_flops_per_step": xla_flops,
        "mfu": mfu,
        "mfu_source": mfu_source,
        "last_loss": progress.get("last_loss"),
    })
    # After the headline is computed (a sweep failure or timeout can no
    # longer cost the metric): the batch sweep + attribution record.
    if os.environ.get("BENCH_SWEEP", "1") != "0":
        # The measured run is already done — only the final emit needs
        # reserving (60 s), not the full measure reserve.
        t = min(450.0, total_budget - (time.time() - t_begin) - 60.0)
        if t < 60.0:
            extra["mfu_sweep"] = {"skipped": "global budget exhausted"}
        else:
            extra["mfu_sweep"] = _mfu_sweep(platform, timeout=t)
    return _emit(round(latency, 3), extra)


if __name__ == "__main__":
    sys.exit(main())
