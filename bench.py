"""End-to-end benchmark: cron-tick → first-train-step latency.

The BASELINE.md north-star metric: a Cron fires, the reconciler instantiates
a JAXJob, the local TPU runtime admits it (topology injection), and the
ResNet-50 workload reaches its first *completed* optimizer step on the
device. Target ≤ 90 s (BASELINE.json; the reference publishes no numbers of
its own — BASELINE.md "Reference-published benchmarks: None").

Runs the full stack in-process on whatever accelerator is visible (the real
TPU chip under the driver): APIServer + Manager(worker pool) +
CronReconciler + LocalExecutor, a Cron on an ``@every 5s`` schedule, and the
``resnet50`` entrypoint (batch 64, 224×224, bf16, SGD).

Prints ONE JSON line:
  {"metric": "tick_to_first_train_step_s", "value": ..., "unit": "s",
   "vs_baseline": <90/value>, "extra": {...}}
"""

from __future__ import annotations

import json
import sys
import time

BASELINE_TARGET_S = 90.0  # BASELINE.json north star
STEPS = 5
BATCH = 64


def main() -> int:
    from cron_operator_tpu.api.scheme import GVK_CRON, default_scheme
    from cron_operator_tpu.backends.local import LocalExecutor
    from cron_operator_tpu.controller import CronReconciler
    from cron_operator_tpu.runtime import APIServer, Manager

    api = APIServer()
    scheme = default_scheme()
    manager = Manager(api, max_concurrent_reconciles=10)
    reconciler = CronReconciler(api, metrics=manager.metrics)
    manager.add_controller(
        "cron", reconciler.reconcile, for_gvk=GVK_CRON,
        owns=scheme.workload_kinds(),
    )
    executor = LocalExecutor(api)

    cron = {
        "apiVersion": "apps.kubedl.io/v1alpha1",
        "kind": "Cron",
        "metadata": {"name": "bench-resnet50", "namespace": "default"},
        "spec": {
            "schedule": "@every 5s",
            "concurrencyPolicy": "Forbid",
            "historyLimit": 3,
            "template": {
                "workload": {
                    "apiVersion": "kubeflow.org/v1",
                    "kind": "JAXJob",
                    "metadata": {
                        "annotations": {
                            "tpu.kubedl.io/entrypoint": "resnet50",
                            "tpu.kubedl.io/param.steps": str(STEPS),
                            "tpu.kubedl.io/param.batch_size": str(BATCH),
                        }
                    },
                    "spec": {"replicaSpecs": {"Worker": {"replicas": 1}}},
                }
            },
        },
    }

    executor.start()
    manager.start()
    api.create(cron)

    deadline = time.time() + 600.0
    job = None
    progress = {}
    try:
        while time.time() < deadline:
            jobs = api.list("kubeflow.org/v1", "JAXJob", namespace="default")
            for j in jobs:
                p = (j.get("status") or {}).get("trainingProgress") or {}
                if p.get("first_step_at"):
                    job, progress = j, p
                    break
            if job is not None:
                break
            time.sleep(0.25)
    finally:
        manager.stop()
        executor.stop()

    if job is None:
        print(json.dumps({
            "metric": "tick_to_first_train_step_s",
            "value": None, "unit": "s", "vs_baseline": 0.0,
            "error": "no job reached its first train step within 600s",
        }))
        return 1

    # Tick anchor: the workload's creationTimestamp. The reconcile that
    # creates it runs on the RequeueAfter timer at the activation instant,
    # so creation time ≈ tick time (the job NAME encodes next_run — one
    # interval in the future, a reference-parity quirk — so it is not a
    # usable anchor). RFC3339 gives whole-second precision; good enough
    # against a 90 s target.
    from cron_operator_tpu.api.v1alpha1 import parse_time

    created = parse_time(job["metadata"]["creationTimestamp"])
    latency = progress["first_step_at"] - created.timestamp()

    import jax

    extra = {
        "model": "resnet50",
        "batch_size": BATCH,
        "backend": jax.default_backend(),
        "n_devices": len(jax.devices()),
        "steps_per_s": progress.get("steps_per_s"),
        "avg_step_time_s": progress.get("avg_step_time_s"),
        "images_per_s": (
            round(BATCH * progress["steps_per_s"], 2)
            if progress.get("steps_per_s") else None
        ),
        "last_loss": progress.get("last_loss"),
        "baseline_target_s": BASELINE_TARGET_S,
    }
    print(json.dumps({
        "metric": "tick_to_first_train_step_s",
        "value": round(latency, 3),
        "unit": "s",
        "vs_baseline": round(BASELINE_TARGET_S / latency, 3),
        "extra": extra,
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
