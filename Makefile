# Development entry points — reference Makefile analog (its test/build
# targets, minus the Go toolchain).

.PHONY: all test gate manifests chart docker-build docker-build-workloads dryrun bench bench-controlplane bench-shards bench-http bench-fleet bench-step chaos-soak chaos-soak-preempt chaos-soak-grow chaos-soak-gray chaos-soak-split chaos-soak-disk chaos-soak-partition obs-report obs-report-dist

all: gate

# Full commit gate: syntax, codegen drift, chart render, test suite.
gate:
	bash hack/ci_gate.sh

test:
	python -m pytest tests/ -q

# Regenerate CRD manifests into deploy/crds and the chart (make manifests).
manifests:
	python -m cron_operator_tpu.api.crd

# Render the chart with default values (helm template analog).
chart:
	python -m cron_operator_tpu.utils.helmtmpl charts/cron-operator-tpu

docker-build:
	docker build -t cron-operator-tpu:latest .

docker-build-workloads:
	docker build -f Dockerfile.workloads -t cron-operator-tpu-workloads:latest .

# Multi-chip sharding compile check on a virtual 8-device CPU mesh.
dryrun:
	python -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

bench:
	python bench.py

# Control-plane throughput/latency at 1k/5k Crons (no device involved):
# steady-state list+reconcile sweep, same-tick fire storm (every Cron due
# on one minute), and a per-verb write-path microbench
# (update/patch_status/create µs). BASELINE=<git-ref> additionally
# measures that ref, reports speedups, and prints a one-line
# OK/REGRESSION verdict over the headline metrics; add CHECK=1 to make a
# regression fail the target.
bench-controlplane:
	python hack/controlplane_bench.py $(if $(BASELINE),--baseline-ref $(BASELINE)) $(if $(CHECK),--check)

# HTTP front-door benchmark (hack/http_bench.py): watch fan-out
# events/s at 1k watchers with the encode-once invariant asserted,
# group-commit durable-write p99 from 1 -> 64 concurrent HTTP writers
# (plus a closed-loop burst that must share fsyncs), APF fairness for a
# quiet tenant under a 50x+ noisy flood (with a single-flow FIFO
# control run), and a zero-steady-state-writes check. Writes
# BENCH_HTTP.json with per-scenario OK/REGRESSION verdicts.
# BASELINE=<git-ref> replays the fan-out scenario against that ref's
# thread-per-connection server and gates the >= 5x speedup; CHECK=1
# runs small sizes and fails the target on any REGRESSION.
bench-http:
	python hack/http_bench.py $(if $(BASELINE),--baseline-ref $(BASELINE)) $(if $(CHECK),--check)

# Sharded control-plane sweep (runtime/shard.py): the same steady-state
# list+reconcile sweep at TOTAL Crons, run per shard count in COUNTS
# (default 1,4). Emits per-shard AND aggregate verdicts into the
# "sharded" key of BENCH_CONTROLPLANE.json; the aggregate is the sum of
# sequentially-measured per-shard throughputs (shared-nothing scale-out
# projection — see PERF.md). Verdict is OK iff aggregate scale-up at the
# highest shard count is >= MIN_SCALEUP (default 3.0) over the 1-shard
# leg AND every shard's steady-state sweep performs zero store writes;
# CHECK=1 makes a REGRESSION fail the target.
TOTAL ?= 100000
COUNTS ?= 1,4
MIN_SCALEUP ?= 3.0
bench-shards:
	python hack/controlplane_bench.py --shards-sweep \
	    --shards-total $(TOTAL) \
	    --shard-counts $(COUNTS) \
	    --shards-min-scaleup $(MIN_SCALEUP) \
	    $(if $(CHECK),--check)

# Fleet scheduler benchmark (hack/fleet_bench.py -> BENCH_FLEET.json):
# a 10k-job fired storm over a mixed v5e/v4/cpu pool, placed by the
# heterogeneity-aware policy vs the FIFO/first-fit baseline under
# identical job physics. Gates: >= 1.5x makespan speedup at
# equal-or-better Jain fairness over per-tenant goodput, placement
# decision p50 <= 1 ms on the tick path, and a wired zero-write
# steady-state leg (repeated scheduler pumps against a real store must
# freeze resourceVersion). CHECK=1 runs a 600-job smoke and fails the
# target on REGRESSION (the CI-gate leg).
bench-fleet:
	python hack/fleet_bench.py $(if $(CHECK),--check --stdout)

# Step-speed benchmark (hack/step_bench.py -> BENCH_STEP.json): the
# overlap-aware executor A/B — seed synchronous path (one dispatch per
# step, inline staging) vs the default scan-chained + double-buffered
# mode on the same MLP run, gated >= 1.3x samples/s with bit-exact
# param parity; plus fused-vs-external, the timed_chain device-compute
# floor, and a Bert-tiny flash-vs-XLA attention leg (tokens/s). CHECK=1
# runs the CI smoke (small sizes, parity + nonzero-overlap asserts, no
# artifact rewrite). SEED_MATRIX=<path> also writes the measured rates
# as a fleet ThroughputMatrix seed sidecar (runtime/fleet.py load_seed).
bench-step:
	python hack/step_bench.py $(if $(CHECK),--check --stdout) \
	    $(if $(SEED_MATRIX),--emit-matrix-seed $(SEED_MATRIX))

# Seeded chaos soak: N Crons reconciled under a deterministic fault
# schedule (conflicts, transient server errors, latency, submit
# failures, watch breaks, leader revocations, slice-preemption storms)
# plus crash-restart rounds (seeded kill-points in the WAL write path,
# recovery from --data-dir), then replayed fault-free from the same
# seed. Asserts the seven invariants documented in README "Durability &
# crash recovery" and writes CHAOS.json; afterwards re-runs the same
# kill schedule WITHOUT durability and requires the restart-integrity
# invariant (I7) to break — the counter-proof that the soak detects the
# loss the WAL prevents. SEED=<n> reproduces a run exactly; N= /
# ROUNDS= scale it.
chaos-soak:
	python hack/chaos_soak.py --seed $(or $(SEED),0) \
	    --crons $(or $(N),200) --rounds $(or $(ROUNDS),6) \
	    --out CHAOS.json
	python hack/chaos_soak.py --seed $(or $(SEED),0) \
	    --crons $(or $(N),200) --rounds $(or $(ROUNDS),6) \
	    --no-durability --expect-violation --out /dev/null
	python hack/chaos_soak.py --processes --seed $(or $(SEED),0) \
	    --crons $(or $(N),200) --rounds $(or $(ROUNDS_PROC),3) \
	    --out CHAOS.json

# Preemption-storm soak (elastic training, I8): the classic soak plus an
# elastic leg where REAL CPU-mesh training jobs (LocalExecutor threads
# over 8 virtual host devices) are preempted mid-run and must resume on
# the surviving devices from their last checkpoint; then the same storm
# WITHOUT elastic resume, which must violate I8 (restart from step 0) —
# the counter-proof that I8 discriminates. See README "Elastic training".
chaos-soak-preempt:
	python hack/chaos_soak.py --seed $(or $(SEED),5) \
	    --crons $(or $(N),24) --rounds $(or $(ROUNDS),2) \
	    --preempt-storm --elastic-jobs $(or $(JOBS),3) \
	    --out CHAOS_PREEMPT.json
	python hack/chaos_soak.py --seed $(or $(SEED),5) \
	    --rounds $(or $(ROUNDS),2) --no-elastic \
	    --elastic-jobs $(or $(JOBS),3) --expect-violation --out /dev/null

# Bidirectional-elasticity soak (grow + shrink-back): the fleet
# capacity-flap leg plus the grow pair — one REAL CPU-mesh training job
# checkpoint-and-regrown into progressively wider idle slices by the
# GrowPlanner, then shrunk back under pinned high-priority pressure,
# measured against the identical shrink-only baseline. Gates: goodput
# margin >= 1.15x and invariants F1-F4 (F4: params bit-exact across
# every width change, restored from the actual soak checkpoints). Folds
# into CHAOS.json; then the counter-proof re-runs the grow scenario
# with the planner OFF and requires a measurable idle chip-second gap
# left on the table. See README "Elastic training".
chaos-soak-grow:
	python hack/chaos_soak.py --seed $(or $(SEED),17) \
	    --crons $(or $(N),12) --rounds $(or $(ROUNDS),2) \
	    --fleet-flap --grow --out CHAOS.json
	python hack/chaos_soak.py --seed $(or $(SEED),17) \
	    --no-grow --expect-violation --out /dev/null

# Gray-failure soak (fencing, watchdogs, breakers): SIGSTOP rounds turn
# a live leader into a zombie mid-lease; the standby must promote with a
# bumped generation and the woken zombie must fence itself before any
# stale-epoch write commits (I10, proven by a byte-level scan of every
# WAL/snapshot for stale-generation records). A router leg SIGSTOPs one
# shard of two and requires its circuit breaker to trip, the healthy
# shard's p99 to stay bounded, tripped calls to fail fast, and the
# breaker to close again after SIGCONT. A hang leg injects silent
# wedges into REAL CPU-mesh training runs; the step watchdog must
# declare HangDetected within its EMA budget and the elastic chain must
# finish every run in one history entry (I11). Then the counter-proof:
# the same SIGSTOP schedule with fencing OFF must land stale-generation
# writes on disk — proof I10 detects the split-brain fencing prevents.
chaos-soak-gray:
	python hack/chaos_soak.py --seed $(or $(SEED),7) \
	    --rounds $(or $(ROUNDS),4) --gray --out CHAOS.json
	python hack/chaos_soak.py --seed $(or $(SEED),7) \
	    --rounds 2 --gray --no-fencing --expect-violation \
	    --out /dev/null

# Live shard-split soak (hack/chaos_soak.py --split -> CHAOS_SPLIT.json):
# live 1->N keyspace splits under a concurrent write storm, with a
# PRF-chosen round that kills the parent's persistence mid-dark-window
# and restarts the whole plane from disk. Every split must hold I6
# (child ≡ filtered replay of the shipped WAL at cutover), I9
# (audit ≡ WAL per shard, including across the kill), I10 (zero
# stale-generation bytes in any WAL/snapshot), S1 (every key has
# exactly ONE owner after each split and after crash-restart — the map
# rename on disk is the commit point), and S2 (no acked write lost).
# Then the counter-proof: the same storm with range fencing OFF must
# ACK a poison write on the demoted parent during the dark window and
# erase it at cutover — proof S2 detects the lost-ack split-brain that
# fencing prevents.
chaos-soak-split:
	python hack/chaos_soak.py --split --seed $(or $(SEED),3) \
	    --crons $(or $(CRONS),60) --rounds $(or $(ROUNDS),3) \
	    --out CHAOS_SPLIT.json
	python hack/chaos_soak.py --split --no-fencing \
	    --seed $(or $(SEED),3) --crons $(or $(CRONS),60) --rounds 2 \
	    --expect-violation --out /dev/null

# Disk-fault soak (hack/chaos_soak.py --disk, invariant I12): cycles
# every DiskFaultInjector kind against one store + data dir — seeded
# bit-flips and mid-file torn writes applied to the closed WAL between
# generations, EIO/ENOSPC injected into append/fsync/rename through the
# syscall seam mid-storm. Proves no corrupted (or never-acked) record is
# ever applied (recovery lands on a verifiable prefix of the acked
# ledger), damage is detected and quarantined with offset/CRC forensics
# plus a scrubber finding on latent cold-segment rot, and injected
# errors fail closed into metrics-visible, probe-healed degraded mode.
# Folds into CHAOS.json; then the counter-proof re-runs the same seeded
# bit-flip with checksums OFF and requires the silent-application
# violation — proof I12a detects what the CRCs exist to catch.
chaos-soak-disk:
	python hack/chaos_soak.py --disk --seed $(or $(SEED),42) \
	    --rounds $(or $(ROUNDS),6) --out CHAOS.json
	python hack/chaos_soak.py --disk --no-checksums \
	    --seed $(or $(SEED),42) --rounds $(or $(ROUNDS),6) \
	    --expect-violation --out /dev/null

# Partition soak (hack/chaos_soak.py --partition, invariant I13): seeded
# in-process socket proxies turn every transport seam into a lying
# network — one-way blackholes, delay/jitter, reordering, duplicated
# frames, slow-drip partial frames, mid-stream RSTs. Proves no acked
# write is lost or doubled across dark windows (the ship-stream book
# check), a leader partitioned from the ROUTER but still heartbeating
# its local lease never false-fails-over (generation pinned, breaker
# fails fast, zero stale-generation bytes), every scheduled partition is
# detected by the ping/pong heartbeat stack and heals within a measured
# bound, and a retry storm at a dark shard leaves the healthy shard's
# write p99 within 1.2x baseline. Folds into CHAOS.json; then the
# counter-proof re-runs the ship leg with heartbeats/read deadlines OFF
# and requires the half-open wedge — proof the detection is not vacuous.
chaos-soak-partition:
	python hack/chaos_soak.py --partition --seed $(or $(SEED),42) \
	    --rounds $(or $(ROUNDS),6) --out CHAOS.json
	python hack/chaos_soak.py --partition --no-net-heartbeats \
	    --seed $(or $(SEED),42) --rounds $(or $(ROUNDS),6) \
	    --expect-violation --out /dev/null

# Observability / SLO report (hack/obs_report.py -> BENCH_OBS.json): the
# flight-recorder scenario (audit ≡ WAL cross-check, lineage traces,
# follower-lag drain) and scheduling-SLO fast legs, plus a real CPU-mesh
# goodput leg (preempt-storm training, productive/elapsed steps vs the
# GOODPUT_FLOOR). One OK/REGRESSION verdict over every leg; CHECK=1 runs
# the fast legs only and fails on REGRESSION (the CI-gate smoke).
obs-report:
	python hack/obs_report.py $(if $(CHECK),--check) \
	    $(if $(SEED),--seed $(SEED))

# Cross-process distributed-tracing leg (hack/obs_report.py
# --distributed -> BENCH_OBS_DIST.json): spawns the REAL supervisor
# topology (router + shard leader + standby, separate OS processes),
# POSTs a Cron through the router's front door under a driver-minted
# traceparent, and requires ONE trace with spans from >= 3 distinct
# processes (router, shard, runner subprocess) whose critical-path
# decomposition (route -> admit -> commit -> fsync -> submit ->
# first_step) reconciles with measured wall latency, I9 on the shard,
# a zero-write debug read path, and the per-frame trace-context
# propagation gate.
obs-report-dist:
	python hack/obs_report.py --distributed --out BENCH_OBS_DIST.json
